"""Polynomial-ring GF(2^w) GEMM — ``strategy="ring"`` (docs/XOR.md).

The ring lowering of arXiv 1701.07731 (Detchart & Lacan): embed each
GF(2^w) symbol into the cyclic polynomial ring ``R_p = F2[x]/(x^p+1)``
for a prime ``p`` with ``ord_p(2) = w``, where multiplying by ``x^s``
is a CYCLIC SHIFT of the coefficient vector — at the packed bit-plane
level a pure reindexing of the plane tuple, zero machine ops.  Every
coefficient multiply then costs only the XOR of a few shifted copies
(the coefficient's *lift weight*, ~2.2 avg for w=8) instead of a dense
w x w bit-matrix.

The embedding that keeps BYTE EQUIVALENCE with the repo's fields
(primitive polys 0x11D / 0x1100B — the acceptance bar for every
strategy) is the ring homomorphism ``psi: R_p -> GF(2^w), x -> g``
with ``g`` an element of order p (``g = alpha^((2^w-1)/p)``):

* ``psi`` is onto (g's minimal polynomial has degree w), its matrix
  ``Psi`` is the w x p bit matrix with column t = bits(g^t);
* ``{g^0..g^(w-1)}`` is an F2-basis, so ``u = sum_j c_j g^j`` with
  ``c = M . bits(u)`` (``M`` = the basis matrix inverse) gives the
  F2-linear lift ``L(u) = sum_j c_j x^j`` satisfying ``psi(L(u)) = u``;
* each coefficient ``a`` lifts to its MINIMUM-WEIGHT preimage among
  the ``2^(p-w)`` solutions of ``Psi z = bits(a)`` (exhaustive for
  w=8's 512-element coset; greedy kernel descent for w=16).

One dispatch is then three straight-line XOR programs over bit planes,
compiled as ONE chain executable between the shared SWAR pack/unpack
stages of :mod:`.xor_gemm`:

1. **ring-in** — per input row, the w byte planes -> w coefficient
   planes via ``M`` (the lift's top ``p - w`` planes are zero and never
   materialise);
2. **accumulate** — per output row r, ring plane ``t`` XORs plane
   ``(t - s) mod p`` of every input i for every ``s`` in the lift
   support of ``A[r, i]`` — the shifts live in the INDEX arithmetic,
   so this stage is nothing but whole-plane XOR;
3. **ring-out** — ``psi`` (+ the wrap-around reduction, already folded
   into the index arithmetic) maps the active ring planes back to w
   byte planes per output row.

Each stage is Paar-CSE'd (same ``paar_cse``), the composite is cached
by matrix digest in-process and in the persistent schedule store
(``kind: "rs_ring_schedule"``, its own ``algo_version``), and the
schedule-optimizer pass (ops/xor_opt.py, ``RS_XOR_OPT``) reorders /
groups / tiles the emitted chain exactly as it does for xor.

Where it stands (docs/XOR.md "Ring lowering" has the numbers): for the
bench k=10/p=4 w=8 encode the ring trades xor's Paar-CSE'd bit-matrix
terms for p/w = 17/8 more intermediate planes; on XLA CPU the extra
plane traffic outweighs the cheaper coefficients, so autotune keeps
picking xor there — the rung exists because the trade flips wherever
whole-region XOR is relatively cheaper than many small ones.  w=16
(p=257, a 16x plane expansion) is a correctness rung only.
"""

from __future__ import annotations

import hashlib
import json
import os
import socket
import threading
import time
from dataclasses import dataclass, replace

import numpy as np

from .gf import get_field
from ..obs import metrics as _metrics, profiler as _prof
from . import xor_gemm as _xg
from .xor_gemm import (
    _COL_ALIGN, PackedOperand, matrix_digest, padded_cols, paar_cse,
)

__all__ = [
    "RingSchedule", "RingPipeline", "build_ring_schedule",
    "gf_matmul_ring", "get_ring_pipeline", "clear_ring_caches",
    "ring_schedule_stats", "ring_pipeline_stats", "ring_store_stats",
    "ring_params",
]

_SUPPORTED_W = (8, 16)

# (p, and the order-p generator exponent (2^w-1)/p) per width: p is the
# smallest prime with ord_p(2) = w, so x^p+1 has a degree-w irreducible
# factor and GF(2^w) contains an order-p element.
_RING_P = {8: 17, 16: 257}


# -- embedding context (pure numpy, cached per w) -----------------------------


def _gf2_solve_affine(Mx: np.ndarray, b: np.ndarray):
    """Particular solution + kernel basis of ``Mx z = b`` over GF(2)."""
    rows, cols = Mx.shape
    A = np.concatenate([Mx.copy(), b.reshape(-1, 1)], axis=1).astype(
        np.uint8
    )
    pivots, r = [], 0
    for c in range(cols):
        piv = next((i for i in range(r, rows) if A[i, c]), None)
        if piv is None:
            continue
        A[[r, piv]] = A[[piv, r]]
        for i in range(rows):
            if i != r and A[i, c]:
                A[i] ^= A[r]
        pivots.append(c)
        r += 1
        if r == rows:
            break
    if any(A[i, cols] for i in range(r, rows)):
        raise ValueError("inconsistent GF(2) system")
    z = np.zeros(cols, np.uint8)
    for i, c in enumerate(pivots):
        z[c] = A[i, cols]
    ker = []
    for f in (c for c in range(cols) if c not in pivots):
        v = np.zeros(cols, np.uint8)
        v[f] = 1
        for i, c in enumerate(pivots):
            v[c] = A[i, f]
        ker.append(v)
    return z, ker


def _gf2_inv(Mx: np.ndarray) -> np.ndarray:
    n = Mx.shape[0]
    A = np.concatenate(
        [Mx.copy().astype(np.uint8), np.eye(n, dtype=np.uint8)], axis=1
    )
    r = 0
    for c in range(n):
        piv = next((i for i in range(r, n) if A[i, c]), None)
        if piv is None:
            raise ValueError("singular GF(2) matrix")
        A[[r, piv]] = A[[piv, r]]
        for i in range(n):
            if i != r and A[i, c]:
                A[i] ^= A[r]
        r += 1
    return A[:, n:]


class _RingCtx:
    """Embedding data for one w: p, Psi (w x p), M (w x w), kernel."""

    __slots__ = ("w", "p", "g", "psi", "m", "kernel", "_lifts", "_gf")

    def __init__(self, w: int):
        gf = get_field(w)
        p = _RING_P[w]

        def fmul(a, b):
            return int(
                np.asarray(
                    gf.mul(
                        np.array([a], gf.dtype), np.array([b], gf.dtype)
                    )
                )[0]
            )

        def fpow(a, e):
            r, base = 1, a
            while e:
                if e & 1:
                    r = fmul(r, base)
                base = fmul(base, base)
                e >>= 1
            return r

        g = fpow(2, (gf.size - 1) // p)  # alpha=2 is primitive for both
        psi = np.zeros((w, p), np.uint8)
        v = 1
        for t in range(p):
            for b in range(w):
                psi[b, t] = (v >> b) & 1
            v = fmul(v, g)
        self.w, self.p, self.g = w, p, g
        self.psi = psi
        self.m = _gf2_inv(psi[:, :w])  # c = M . bits(u)
        _, self.kernel = _gf2_solve_affine(
            psi, np.zeros(w, np.uint8)
        )
        self._lifts: dict[int, np.ndarray] = {}
        self._gf = gf

    def lift(self, a: int) -> np.ndarray:
        """Minimum-weight (w=8: exact; w=16: greedy) preimage of ``a``
        under psi, as a p-length 0/1 vector."""
        hit = self._lifts.get(a)
        if hit is not None:
            return hit
        bits = np.array(
            [(a >> b) & 1 for b in range(self.w)], np.uint8
        )
        z, ker = _gf2_solve_affine(self.psi, bits)
        if self.w == 8:
            # 2^(17-8) = 512 coset elements — exhaustive minimum.
            K = np.array(ker, np.uint8)
            best, bw = z, int(z.sum())
            for mask in range(1, 1 << len(ker)):
                v = z.copy()
                mm, i = mask, 0
                while mm:
                    if mm & 1:
                        v ^= K[i]
                    mm >>= 1
                    i += 1
                wt = int(v.sum())
                if wt < bw:
                    best, bw = v, wt
            z = best
        else:
            # Greedy steepest descent over the kernel basis — the
            # 2^241 coset is not enumerable, but its size is exactly
            # why low-weight members are dense (weights 1-4 observed
            # for the test matrices).  Deterministic.
            K = np.array(ker, np.uint8)
            while True:
                cand = z ^ K
                wts = cand.sum(axis=1)
                i = int(wts.argmin())
                if wts[i] >= z.sum():
                    break
                z = cand[i]
        self._lifts[a] = z
        return z


_CTX_CACHE: dict[int, _RingCtx] = {}
_CTX_LOCK = threading.Lock()


def _ctx(w: int) -> _RingCtx:
    with _CTX_LOCK:
        hit = _CTX_CACHE.get(w)
        if hit is None:
            hit = _CTX_CACHE[w] = _RingCtx(w)
        return hit


def ring_params(w: int) -> dict:
    """Embedding facts for docs/doctor: p, generator, avg basis density."""
    c = _ctx(w)
    return {
        "w": w,
        "p": c.p,
        "g": c.g,
        "psi_density": round(float(c.psi.mean()), 4),
        "plane_expansion": round(c.p / w, 4),
    }


# -- schedule -----------------------------------------------------------------


@dataclass(frozen=True)
class RingSchedule:
    """Three Paar-CSE'd straight-line XOR programs (hashable, immutable).

    Stage s consumes the previous stage's output planes (stage 1: the
    ``k * w`` packed byte planes) — ``sN_pairs[t] = (a, b)`` defines
    node ``n_inputs_N + t``; ``sN_rows`` lists each output plane's term
    nodes (empty tuple -> zero plane).  ``s2_planes`` records which
    ``(out_row, ring_plane_t)`` each stage-2 output is — stage 3's term
    indices point into that list.
    """

    digest: str
    w: int
    p: int
    rows_out: int
    k: int
    n_inputs: int
    s1_pairs: tuple
    s1_rows: tuple
    s2_pairs: tuple
    s2_rows: tuple
    s2_planes: tuple
    s3_pairs: tuple
    s3_rows: tuple
    terms_naive: int
    terms_cse: int
    cse: bool
    build_seconds: float

    @property
    def xors(self) -> int:
        """XOR ops one dispatch evaluates (per packed word column)."""
        return sum(
            len(pairs) + sum(max(0, len(r) - 1) for r in rows)
            for pairs, rows in (
                (self.s1_pairs, self.s1_rows),
                (self.s2_pairs, self.s2_rows),
                (self.s3_pairs, self.s3_rows),
            )
        )

    @property
    def stage_payloads(self) -> tuple:
        return (
            (self.s1_pairs, self.s1_rows),
            (self.s2_pairs, self.s2_rows),
            (self.s3_pairs, self.s3_rows),
        )


_SCHEDULE_CACHE: dict[tuple, RingSchedule] = {}
_SCHEDULE_LOCK = threading.Lock()


# Paar's elimination argmaxes an O((n_inputs + pairs)^2) co-occurrence
# counter per extracted pair; ring stage programs can carry thousands of
# input planes (the p/w expansion — stage 3 of a w=16 decode sees one
# input per ACTIVE ring plane), where that turns into minutes of
# elimination for single-digit XOR savings.  Stages past this size run
# naive: byte-identical output, just no shared nodes.
_CSE_STAGE_BOUND = 2048


def _stage_program(row_sets, n_inputs: int, cse: bool):
    """(pair_ops, rows) for one stage, Paar-CSE'd when enabled."""
    sets = [set(s) for s in row_sets]
    if cse and 0 < max(n_inputs, len(sets)) <= _CSE_STAGE_BOUND \
            and n_inputs > 0:
        pair_ops, sets = paar_cse(sets, n_inputs)
    else:
        pair_ops = []
    return (
        tuple(pair_ops),
        tuple(tuple(int(t) for t in sorted(s)) for s in sets),
    )


# -- persistent store (kind: rs_ring_schedule) --------------------------------
#
# Same contract as the xor store (docs/XOR.md "The persistent store"):
# pure data keyed by (digest, cse, algo version), every load fully
# validated, corruption recomputes.  v1 is the first ring algorithm;
# records carry an explicit ``algo_version`` from day one.

_STORE_ALGO = 1

_STORE_LOCK = threading.Lock()
_STORE_INDEX: dict[tuple, dict] | None = None
_STORE_STATS = {"hits": 0, "misses": 0, "stored": 0, "corrupt": 0,
                "built": 0}


def _count_store(outcome: str) -> None:
    _metrics.counter(
        "rs_ring_schedule_store_total",
        "persistent ring-schedule store lookups by outcome",
    ).labels(outcome=outcome).inc()


def _store_path() -> str | None:
    from ..obs import runlog as _runlog

    return _runlog.store_path()


def _rec_ts(rec: dict) -> float:
    try:
        return float(rec.get("ts") or 0.0)
    except (TypeError, ValueError):
        return 0.0


def _store_index() -> dict[tuple, dict]:
    global _STORE_INDEX
    with _STORE_LOCK:
        if _STORE_INDEX is not None:
            return _STORE_INDEX
    p = _store_path()
    idx: dict[tuple, dict] = {}
    if p:
        from ..obs import runlog as _runlog

        for rec in _runlog.read_records(p):
            if rec.get("kind") != "rs_ring_schedule":
                continue
            digest = rec.get("digest")
            if not isinstance(digest, str):
                continue
            key = (digest, bool(rec.get("cse")))
            cur = idx.get(key)
            if cur is None or _rec_ts(rec) >= _rec_ts(cur):
                idx[key] = rec
    with _STORE_LOCK:
        if _STORE_INDEX is None:
            _STORE_INDEX = idx
        return _STORE_INDEX


def _reset_store_index() -> None:
    global _STORE_INDEX
    with _STORE_LOCK:
        _STORE_INDEX = None


def _payload_digest(sched_fields: dict) -> str:
    h = hashlib.blake2b(digest_size=8)
    h.update(
        json.dumps(sched_fields, separators=(",", ":")).encode()
    )
    return h.hexdigest()


def _stage_fields(s1_pairs, s1_rows, s2_pairs, s2_rows, s2_planes,
                  s3_pairs, s3_rows) -> dict:
    return {
        "s1_pairs": [list(x) for x in s1_pairs],
        "s1_rows": [list(r) for r in s1_rows],
        "s2_pairs": [list(x) for x in s2_pairs],
        "s2_rows": [list(r) for r in s2_rows],
        "s2_planes": [list(x) for x in s2_planes],
        "s3_pairs": [list(x) for x in s3_pairs],
        "s3_rows": [list(r) for r in s3_rows],
    }


def _validate_stage(pair_ops, rows, n_inputs: int, n_rows: int | None):
    for t, (a, b) in enumerate(pair_ops):
        if not (0 <= a < n_inputs + t and 0 <= b < n_inputs + t):
            raise ValueError("pair op references an undefined node")
    n_nodes = n_inputs + len(pair_ops)
    for r in rows:
        for t in r:
            if not 0 <= t < n_nodes:
                raise ValueError("row term references an undefined node")
    if n_rows is not None and len(rows) != n_rows:
        raise ValueError("stage row count inconsistent")


def _schedule_from_store(digest: str, cse: bool, A: np.ndarray,
                         w: int) -> RingSchedule | None:
    if not _store_path():
        return None
    rec = _store_index().get((digest, cse))
    if rec is None:
        with _STORE_LOCK:
            _STORE_STATS["misses"] += 1
        _count_store("miss")
        return None
    try:
        if rec.get("algo_version") != _STORE_ALGO:
            raise ValueError("algorithm version mismatch")
        rows_out, k = int(rec["rows_out"]), int(rec["k"])
        n_inputs, p = int(rec["n_inputs"]), int(rec["p"])
        if (int(rec["w"]), rows_out, k) != (w, A.shape[0], A.shape[1]):
            raise ValueError("shape fields disagree with the matrix")
        if n_inputs != k * w or p != _RING_P[w]:
            raise ValueError("ring parameters inconsistent with (k, w)")
        s1_pairs = tuple((int(a), int(b)) for a, b in rec["s1_pairs"])
        s1_rows = tuple(
            tuple(int(t) for t in r) for r in rec["s1_rows"]
        )
        s2_pairs = tuple((int(a), int(b)) for a, b in rec["s2_pairs"])
        s2_rows = tuple(
            tuple(int(t) for t in r) for r in rec["s2_rows"]
        )
        s2_planes = tuple(
            (int(r_), int(t)) for r_, t in rec["s2_planes"]
        )
        s3_pairs = tuple((int(a), int(b)) for a, b in rec["s3_pairs"])
        s3_rows = tuple(
            tuple(int(t) for t in r) for r in rec["s3_rows"]
        )
        _validate_stage(s1_pairs, s1_rows, n_inputs, k * w)
        _validate_stage(s2_pairs, s2_rows, k * w, len(s2_planes))
        if len(s2_rows) != len(s2_planes):
            raise ValueError("stage-2 plane map inconsistent")
        for r_, t in s2_planes:
            if not (0 <= r_ < rows_out and 0 <= t < p):
                raise ValueError("stage-2 plane id out of range")
        _validate_stage(
            s3_pairs, s3_rows, len(s2_planes), rows_out * w
        )
        fields = _stage_fields(
            s1_pairs, s1_rows, s2_pairs, s2_rows, s2_planes,
            s3_pairs, s3_rows,
        )
        if rec.get("payload_digest") != _payload_digest(fields):
            raise ValueError("payload checksum mismatch")
        sched = RingSchedule(
            digest=digest, w=w, p=p, rows_out=rows_out, k=k,
            n_inputs=n_inputs,
            s1_pairs=s1_pairs, s1_rows=s1_rows,
            s2_pairs=s2_pairs, s2_rows=s2_rows, s2_planes=s2_planes,
            s3_pairs=s3_pairs, s3_rows=s3_rows,
            terms_naive=int(rec["terms_naive"]),
            terms_cse=int(rec["terms_cse"]),
            cse=cse, build_seconds=0.0,
        )
    except Exception:
        with _STORE_LOCK:
            if _STORE_INDEX is not None:
                _STORE_INDEX.pop((digest, cse), None)
            _STORE_STATS["corrupt"] += 1
        _count_store("corrupt")
        return None
    with _STORE_LOCK:
        _STORE_STATS["hits"] += 1
    _count_store("hit")
    return sched


def _schedule_to_store(sched: RingSchedule) -> None:
    p = _store_path()
    if not p:
        return
    key = (sched.digest, sched.cse)
    idx = _store_index()
    if key in idx:
        return
    from ..obs import runlog as _runlog

    fields = _stage_fields(
        sched.s1_pairs, sched.s1_rows, sched.s2_pairs, sched.s2_rows,
        sched.s2_planes, sched.s3_pairs, sched.s3_rows,
    )
    rec = {
        "kind": "rs_ring_schedule",
        "schema": _runlog.SCHEMA_VERSION,
        "algo_version": _STORE_ALGO,
        "digest": sched.digest,
        "cse": sched.cse,
        "w": sched.w,
        "p": sched.p,
        "rows_out": sched.rows_out,
        "k": sched.k,
        "n_inputs": sched.n_inputs,
        **fields,
        "payload_digest": _payload_digest(fields),
        "terms_naive": sched.terms_naive,
        "terms_cse": sched.terms_cse,
        "build_seconds": round(sched.build_seconds, 6),
        "ts": time.time(),
        "run": _runlog.run_id(),
        "host": socket.gethostname(),
    }
    _runlog.append(rec, p)
    with _STORE_LOCK:
        if _STORE_INDEX is not None:
            _STORE_INDEX[key] = rec
        _STORE_STATS["stored"] += 1
    _count_store("stored")


def ring_store_stats(load: bool = False) -> dict:
    """Ring store facts for `rs doctor` (mirrors xor store_stats)."""
    p = _store_path()
    if load and p:
        _store_index()
    with _STORE_LOCK:
        entries = (
            len(_STORE_INDEX) if _STORE_INDEX is not None else None
        )
        out = dict(_STORE_STATS)
    out.update({"path": p, "enabled": p is not None, "entries": entries})
    return out


# -- schedule build -----------------------------------------------------------


def build_ring_schedule(A, w: int, cse: bool | None = None) -> RingSchedule:
    """Ring-lower ``A`` into the three stage programs — cached by digest
    in-process, then by the persistent store, then computed."""
    if w not in _SUPPORTED_W:
        raise ValueError(
            f"strategy='ring' supports w in {_SUPPORTED_W}, got w={w}"
        )
    if cse is None:
        cse = _xg._cse_enabled()
    A = np.asarray(A)
    digest = matrix_digest(A, w)
    key = (digest, bool(cse))
    with _SCHEDULE_LOCK:
        hit = _SCHEDULE_CACHE.get(key)
    if hit is not None:
        _prof.attr(schedule="memory")
        return hit
    loaded = _schedule_from_store(digest, bool(cse), A, w)
    if loaded is not None:
        _prof.attr(schedule="store")
        with _SCHEDULE_LOCK:
            return _SCHEDULE_CACHE.setdefault(key, loaded)
    _prof.attr(schedule="built")
    with _STORE_LOCK:
        _STORE_STATS["built"] += 1
    t0 = time.perf_counter()
    ctx = _ctx(w)
    p = ctx.p
    rows_out, k = A.shape
    n_inputs = k * w

    # Stage 1 — ring-in: c_{i,j} = sum_b M[j,b] u_{i,b}.
    s1_sets = [
        {i * w + b for b in np.flatnonzero(ctx.m[j])}
        for i in range(k) for j in range(w)
    ]

    # Stage 2 — shift-accumulate: ring plane (r, t) is the parity-XOR
    # of c-planes (i, j) over every lift support s with (j+s) % p == t.
    # The cyclic shifts are pure index arithmetic here — the executable
    # never shifts anything.
    terms: list[dict[int, set[int]]] = [dict() for _ in range(rows_out)]
    for r in range(rows_out):
        for i in range(k):
            a = int(A[r, i])
            if not a:
                continue
            lift = ctx.lift(a)
            for s in np.flatnonzero(lift):
                for j in range(w):
                    t = (j + int(s)) % p
                    bucket = terms[r].setdefault(t, set())
                    c_idx = i * w + j
                    if c_idx in bucket:
                        bucket.discard(c_idx)  # parity cancellation
                    else:
                        bucket.add(c_idx)
    s2_planes: list[tuple[int, int]] = []
    s2_sets: list[set[int]] = []
    for r in range(rows_out):
        for t in sorted(terms[r]):
            if terms[r][t]:
                s2_planes.append((r, t))
                s2_sets.append(terms[r][t])
    plane_index = {rt: i for i, rt in enumerate(s2_planes)}

    # Stage 3 — ring-out: bits(out_r)[b] = sum_t Psi[b,t] S_r[t]
    # (inactive ring planes are identically zero and drop out).
    s3_sets = []
    for r in range(rows_out):
        for b in range(w):
            s3_sets.append({
                plane_index[(r, t)]
                for t in np.flatnonzero(ctx.psi[b])
                if (r, int(t)) in plane_index
            })

    naive = sum(len(s) for s in s1_sets + s2_sets + s3_sets)
    limit = _xg._max_terms()
    if naive > limit:
        raise ValueError(
            f"ring schedule for {rows_out}x{k} w={w} needs {naive} XOR "
            f"terms, over RS_XOR_MAX_TERMS={limit}; use strategy='xor' "
            "(or raise the knob) for matrices this large"
        )
    s1_pairs, s1_rows = _stage_program(s1_sets, n_inputs, bool(cse))
    s2_pairs, s2_rows = _stage_program(s2_sets, n_inputs, bool(cse))
    s3_pairs, s3_rows = _stage_program(
        s3_sets, len(s2_planes), bool(cse)
    )
    terms_cse = (
        len(s1_pairs) + len(s2_pairs) + len(s3_pairs)
        + sum(len(r) for r in s1_rows + s2_rows + s3_rows)
    )
    dt = time.perf_counter() - t0
    _metrics.quantile(
        "rs_ring_schedule_build_seconds",
        "ring-schedule lowering+CSE wall seconds (streaming quantiles)",
    ).observe(dt)
    sched = RingSchedule(
        digest=digest, w=w, p=p, rows_out=rows_out, k=k,
        n_inputs=n_inputs,
        s1_pairs=s1_pairs, s1_rows=s1_rows,
        s2_pairs=s2_pairs, s2_rows=s2_rows,
        s2_planes=tuple(s2_planes),
        s3_pairs=s3_pairs, s3_rows=s3_rows,
        terms_naive=naive, terms_cse=terms_cse,
        cse=bool(cse), build_seconds=dt,
    )
    _schedule_to_store(sched)
    with _SCHEDULE_LOCK:
        return _SCHEDULE_CACHE.setdefault(key, sched)


def ring_schedule_stats() -> list[dict]:
    """Built ring schedules — the `rs doctor` surface."""
    with _SCHEDULE_LOCK:
        scheds = list(_SCHEDULE_CACHE.values())
    return [
        {
            "digest": s.digest,
            "w": s.w,
            "p": s.p,
            "rows_out": s.rows_out,
            "k": s.k,
            "cse": s.cse,
            "ring_planes": len(s.s2_planes),
            "terms_naive": s.terms_naive,
            "terms_cse": s.terms_cse,
            "xors": s.xors,
            "build_seconds": round(s.build_seconds, 6),
        }
        for s in scheds
    ]


# -- chain emission -----------------------------------------------------------


def _emit_slp(inputs, pair_ops, rows, zero_ref):
    """One straight-line XOR program: inputs + pair nodes -> row trees.
    ``zero_ref`` shapes the zero planes of empty rows — a stage fed by
    an all-zero coefficient row can have NO inputs at all."""
    import jax.numpy as jnp

    nodes = list(inputs)
    for a, b in pair_ops:
        nodes.append(nodes[a] ^ nodes[b])
    return tuple(
        _xg._xor_tree([nodes[t] for t in terms]) if terms
        else jnp.zeros_like(zero_ref)
        for terms in rows
    )


def _ring_chain_stage(nodes, sched: RingSchedule):
    """ring-in |> shift-accumulate |> ring-out, one traced program."""
    ref = nodes[0]
    c = _emit_slp(nodes, sched.s1_pairs, sched.s1_rows, ref)
    s2 = _emit_slp(c, sched.s2_pairs, sched.s2_rows, ref)
    return _emit_slp(s2, sched.s3_pairs, sched.s3_rows, ref)


# -- compiled pipeline --------------------------------------------------------


class RingPipeline:
    """pack |> ring chain |> unpack for one (schedule, k, cols, dtype).

    Same shell as :class:`..ops.xor_gemm.XorPipeline` — the pack /
    unpack executables ARE xor's (shared per-class stage cache; a
    :class:`PackedOperand` packed for xor feeds ring unchanged), only
    the chain differs.  The optimizer pass applies per stage program
    and tiles the whole chain.
    """

    __slots__ = (
        "schedule", "k", "cols", "dtype", "compile_seconds",
        "cost_analysis", "calls", "opt", "_pack", "_chain", "_unpack",
        "_pieces", "_assemble", "_emit", "_split",
    )

    def __init__(self, schedule: RingSchedule, k: int, cols: int, dtype):
        import jax

        from . import xor_opt as _xopt

        if cols % _COL_ALIGN:
            raise ValueError(
                f"ring pipeline cols must be {_COL_ALIGN}-aligned, "
                f"got {cols}"
            )
        self.schedule = schedule
        self.k = k
        self.cols = cols
        self.dtype = np.dtype(dtype)
        self.calls = 0
        t0 = time.perf_counter()
        w = schedule.w
        emit = schedule
        n_planes = schedule.n_inputs + sum(
            len(pairs) + len(rows)
            for pairs, rows in schedule.stage_payloads
        )
        nw = cols // _COL_ALIGN
        if _xopt.opt_enabled():
            moved = groups = 0
            fields = {}
            for name, n_in in (
                ("s1", schedule.n_inputs),
                ("s2", schedule.n_inputs),
                ("s3", len(schedule.s2_planes)),
            ):
                pairs, rows, mv, gr = _xopt.optimize_program(
                    getattr(schedule, f"{name}_pairs"),
                    getattr(schedule, f"{name}_rows"),
                    n_in,
                )
                fields[f"{name}_pairs"] = pairs
                fields[f"{name}_rows"] = rows
                moved += mv
                groups += gr
            emit = replace(schedule, **fields)
            tile, n_tiles, ws = _xopt.choose_tile(n_planes, nw)
            self.opt = _xopt.OptStats(
                enabled=True, nodes_moved=moved, term_groups=groups,
                tile_words=tile, n_tiles=n_tiles,
                est_working_set_bytes=ws,
                split_unpack=_xopt.split_unpack(nw),
            )
        else:
            self.opt = _xopt.disabled_stats()
        self._pack = _xg._pack_exe(k, cols, self.dtype, w)
        nodes_struct = tuple(
            [_xg._plane_struct(cols)] * (k * w)
        )
        tile = self.opt.tile_words
        if tile:
            # The xor tiled-scan walker takes any object with
            # ``pair_ops``/``rows`` — adapt the three-stage chain by
            # running it as the block function via a shim schedule.
            chain_fn = (
                lambda ns: _tiled_ring_chain(ns, emit, tile)
            )
        else:
            chain_fn = lambda ns: _ring_chain_stage(ns, emit)
        self._chain = (
            jax.jit(chain_fn).lower(nodes_struct).compile()
        )
        # The emitted (post-optimizer) schedule is retained so a profiled
        # dispatch (obs/profiler.py) can lazily compile the three stage
        # programs SPLIT (ring-in / shift-accumulate / ring-out) and time
        # each; the hot path always runs the fused self._chain.
        self._emit = emit
        self._split = False  # False = not built; None = not splittable
        if self.opt.split_unpack:
            self._unpack = None
            self._pieces = _xg._pieces_exe(schedule.rows_out, cols, w)
            self._assemble = _xg._assemble_exe(
                schedule.rows_out, cols, w
            )
        else:
            self._unpack = _xg._unpack_exe(schedule.rows_out, cols, w)
            self._pieces = self._assemble = None
        self.compile_seconds = time.perf_counter() - t0
        self.cost_analysis = self._merged_cost()

    def _split_exes(self):
        """The three ring stage programs as separate executables, built
        on the first PROFILED dispatch (never the hot path: the fused
        chain stays the dispatch executable).  The split is the same
        ``_emit_slp`` composition as :func:`_ring_chain_stage` — pure
        XOR, so outputs are byte-identical; it is not region-tiled
        (stage walls, not cache-residency, are what it measures).
        Returns None for degenerate schedules with no active ring
        planes (stage 3 would have no input to shape its zeros from)."""
        if self._split is False:
            import jax

            emit = self._emit
            if not self.schedule.s2_planes:
                self._split = None
                return None

            def stage_fn(pairs, rows):
                return lambda ns: _emit_slp(ns, pairs, rows, ns[0])

            t0 = time.perf_counter()
            plane = _xg._plane_struct(self.cols)
            split = []
            for pairs, rows, n_in in (
                (emit.s1_pairs, emit.s1_rows, emit.n_inputs),
                (emit.s2_pairs, emit.s2_rows, len(emit.s1_rows)),
                (emit.s3_pairs, emit.s3_rows, len(emit.s2_rows)),
            ):
                split.append(
                    jax.jit(stage_fn(pairs, rows))
                    .lower(tuple([plane] * n_in))
                    .compile()
                )
            dt = time.perf_counter() - t0
            self.compile_seconds += dt
            _prof.add_compile(dt)
            self._split = tuple(split)
        return self._split

    def _merged_cost(self):
        from ..obs.attrib import extract_cost_analysis

        stages = (
            (self._pack, self._chain, self._unpack)
            if self._unpack is not None
            else (self._pack, self._chain, self._pieces, self._assemble)
        )
        total: dict = {}
        for exe in stages:
            ca = extract_cost_analysis(exe)
            if not ca:
                return None
            for key, v in ca.items():
                total[key] = total.get(key, 0.0) + v
        return total or None

    def __call__(self, A, B):
        self.calls += 1
        # One thread-local read: with no RS_PROF profile open this call
        # is the unchanged fused-chain dispatch.
        prof = _prof.active()
        if isinstance(B, PackedOperand):
            if (B.rows, B.cols, B.w) != (
                self.k, self.cols, self.schedule.w
            ) or B.dtype != self.dtype:
                raise ValueError(
                    f"packed operand ({B.rows}x{B.cols}, w={B.w}, "
                    f"{B.dtype}) does not match pipeline "
                    f"({self.k}x{self.cols}, w={self.schedule.w}, "
                    f"{self.dtype})"
                )
            _xg._count_pack_reuse("reused")
            if prof is not None:
                _prof.attr(pack="reused")
            planes = B.planes
        else:
            _xg._count_pack_reuse("packed")
            if prof is None:
                planes = _xg._observed_pack(self._pack, B)
            else:
                _prof.attr(pack="packed")
                planes = _prof.run_stage("pack", self._pack, B)
        if prof is None:
            outs = self._chain(planes)
            if self._unpack is not None:
                return self._unpack(outs)
            return self._assemble(self._pieces(outs))
        # Profiled dispatch: run the three ring stages SPLIT so each
        # gets its own blocked wall (byte-identical to the fused chain
        # — see _split_exes).
        split = self._split_exes()
        if split is None:
            outs = _prof.run_stage("chain", self._chain, planes)
        else:
            s1, s2, s3 = split
            c = _prof.run_stage("ring_in", s1, planes)
            acc = _prof.run_stage("shift_acc", s2, c)
            outs = _prof.run_stage("ring_out", s3, acc)
        if self._unpack is not None:
            return _prof.run_stage("unpack", self._unpack, outs)
        return _prof.run_stage(
            "unpack", lambda o: self._assemble(self._pieces(o)), outs
        )

    def describe(self) -> dict:
        s = self.schedule
        return {
            "digest": s.digest,
            "w": s.w,
            "p": s.p,
            "k": self.k,
            "rows_out": s.rows_out,
            "cols": self.cols,
            "cse": s.cse,
            "ring_planes": len(s.s2_planes),
            "terms_naive": s.terms_naive,
            "terms_cse": s.terms_cse,
            "xors": s.xors,
            "calls": self.calls,
            "compile_seconds": round(self.compile_seconds, 6),
            "opt": self.opt.as_dict(),
        }


def _tiled_ring_chain(nodes, sched: RingSchedule, tile: int):
    """Region-tiled three-stage ring chain (ops/xor_opt.py): same scan
    shape as xor's tiled chain, with the composite stage program as the
    per-tile block."""
    import jax.numpy as jnp
    from jax import lax

    nodes = tuple(nodes)
    nw = nodes[0].shape[0]
    nt, tail = nw // tile, nw % tile

    def step(carry, t):
        off = t * tile
        sl = tuple(
            lax.dynamic_slice(p_, (off,), (tile,)) for p_ in nodes
        )
        outs = _ring_chain_stage(sl, sched)
        carry = tuple(
            lax.dynamic_update_slice(c, o, (off,))
            for c, o in zip(carry, outs)
        )
        return carry, None

    init = tuple(
        jnp.zeros((nw,), nodes[0].dtype)
        for _ in range(sched.rows_out * sched.w)
    )
    out, _ = lax.scan(step, init, jnp.arange(nt))
    if tail:
        sl = tuple(p_[nt * tile:] for p_ in nodes)
        outs = _ring_chain_stage(sl, sched)
        out = tuple(
            lax.dynamic_update_slice(c, o, (nt * tile,))
            for c, o in zip(out, outs)
        )
    return out


_PIPELINE_CACHE: dict[tuple, RingPipeline] = {}
_PIPELINE_LOCK = threading.Lock()


def get_ring_pipeline(A, B_shape, B_dtype, w: int) -> RingPipeline:
    """Build-or-fetch the compiled ring pipeline for concrete ``A`` and
    a (k, cols) operand class (cols 32-aligned, see padded_cols)."""
    from . import xor_opt as _xopt

    schedule = build_ring_schedule(A, w)
    k, cols = B_shape
    key = (
        schedule.digest, schedule.cse, k, cols,
        np.dtype(B_dtype).str, _xopt.env_fingerprint(),
    )
    with _PIPELINE_LOCK:
        pipe = _PIPELINE_CACHE.get(key)
        if pipe is None:
            pipe = _PIPELINE_CACHE[key] = RingPipeline(
                schedule, k, cols, B_dtype
            )
        return pipe


def clear_ring_caches() -> None:
    """Drop ring pipelines + schedules and forget the store index (the
    store FILE survives — pure data, revalidated on next load).  Runs
    automatically with :func:`..ops.xor_gemm.clear_pipeline_cache`
    (registered hook): ring pipelines pin stage executables from xor's
    just-cleared shared cache."""
    with _PIPELINE_LOCK:
        _PIPELINE_CACHE.clear()
    with _SCHEDULE_LOCK:
        _SCHEDULE_CACHE.clear()
    _reset_store_index()


_xg.register_clear_hook(clear_ring_caches)


def ring_pipeline_stats() -> list[dict]:
    with _PIPELINE_LOCK:
        pipes = list(_PIPELINE_CACHE.values())
    return [p.describe() for p in pipes]


def gf_matmul_ring(A, B, w: int = 8):
    """``C = A . B`` over GF(2^w) via the ring pipeline (eager entry).

    Same contract as :func:`..ops.xor_gemm.gf_matmul_xor`: ``A`` must
    be concrete (its values select the schedule), ``B`` may be traced
    (the stage programs inline under the caller's jit), ragged widths
    pad to the 32-symbol alignment and trim after.
    """
    import jax
    import jax.numpy as jnp

    if isinstance(A, jax.core.Tracer):
        raise TypeError(
            "strategy='ring' needs concrete coefficient values to build "
            "its ring schedule; call it outside jit (or via the plan "
            "layer), not on a traced A"
        )
    A = np.asarray(A)
    gf = get_field(w)
    dtype = np.dtype(gf.dtype)
    rows_out, k = A.shape
    m = B.shape[1]
    if m == 0:
        return jnp.zeros((rows_out, 0), dtype=dtype)
    cols = padded_cols(m)
    if B.shape[1] != cols:
        B = jnp.asarray(B)
        B = jnp.pad(B, ((0, 0), (0, cols - m)))
    if isinstance(B, jax.core.Tracer):
        schedule = build_ring_schedule(A, w)
        out = _xg._unpack_stage(
            _ring_chain_stage(_xg._pack_stage(B, w), schedule),
            schedule.w, schedule.rows_out, cols,
        )
    else:
        pipe = get_ring_pipeline(A, (k, cols), dtype, w)
        out = pipe(A, B)
    return out[:, :m] if cols != m else out
