"""XOR-lowered bitsliced GF(2^w) GEMM — ``strategy="xor"`` (docs/XOR.md).

The table strategy gathers, the bitplane strategy matmuls; this strategy
does neither: it lowers the tiny GF(2^w) coefficient matrix to its
``(rows*w, k*w)`` GF(2) binary equivalent (each symbol becomes the w x w
bit-matrix of multiply-by-that-constant, ``gf.bitmatrix``) and evaluates
the product as **pure XOR accumulation over packed bit-planes** — the
scheme the XOR-EC literature vectorizes with SIMD (arXiv 2108.02692,
arXiv 1909.02871) and Jerasure calls bit-matrix coding, expressed here
in XLA uint32 ops so it runs identically on CPU and TPU backends with no
lookup tables and no native extension.

Three stages, compiled as three AOT executables per (matrix digest,
shape bucket) and composed by :class:`XorPipeline`:

* **pack** — bit-transpose each data row into w bit-plane vectors of
  packed uint32 words.  An 8x8 bit transpose costs 3 rounds of SWAR
  delta-swaps (Hacker's Delight 7-3, little-endian variant) plus a 4x4
  byte transpose done with shift/mask ops.  Word pairing uses
  *contiguous half/quarter splits* instead of memory-strided pairs: the
  XOR algebra only needs every plane to list symbol bits in the SAME
  position order, not in any PARTICULAR order, so the layout is chosen
  to make every load contiguous and the unpack a pure concatenation —
  measured ~2x over the strided form on XLA CPU.
* **xor chain** — one XOR-tree per output plane, selected by the binary
  matrix rows, after greedy pair-frequency CSE (Paar's algorithm) has
  rewritten shared column pairs into reusable intermediate nodes.
  Planes travel as TUPLES of separate arrays: stacking them into one
  (planes, words) array forces XLA CPU through a layout copy that was
  measured 3x slower than the tuple form.
* **unpack** — the inverse transform on the ``rows_out * w`` output
  planes; with the contiguous-split pairing this is elementwise ops plus
  one concatenate, then a bitcast back to uint8/uint16 symbols.

The stages are deliberately SEPARATE executables: fused into one XLA
program, the compiler rematerializes pack subexpressions into every
chain consumer and the whole thing runs ~2x slower than the sum of its
parts (measured on XLA CPU; see docs/XOR.md for the numbers).

Warm-path amortization (this file's other half):

* **Persistent schedule store** — built schedules serialize into the
  run-ledger-backed store (``obs.runlog.store_path()``: rides
  ``RS_RUNLOG`` unless ``RS_SCHEDULE_STORE`` names its own path or
  disables it), so a fresh CLI process or a restarted ``rs serve``
  daemon loads the Paar-CSE result by matrix digest instead of
  re-running the elimination.  Loads are validated (algorithm version,
  shape fields, node-index bounds, payload checksum); anything torn or
  foreign falls back to a recompute — never a crash, never a wrong
  schedule (``rs_schedule_store_total{outcome}``).
* **Packed-operand reuse** — :class:`PackedOperand` carries a staged
  segment's bit-planes between chained dispatches that consume the same
  ``B`` (locate decode's syndrome + recovery GEMMs), so the second
  consumer skips the pack stage entirely.  Pack wall is its own metric
  (``rs_xor_pack_seconds``, recorded only under ``RS_XOR_PACK_TIMING=1``
  + metrics — the timing must block on the planes, so it is opt-in on
  top of RS_METRICS and the production path never loses its async
  pack->chain overlap).
* **Shared stage executables** — pack/unpack depend only on the operand
  class (rows, cols, dtype, w), not the schedule, so they compile once
  per class and are shared across every pipeline (decode survivor-set
  churn no longer recompiles the transpose machinery per subset).

Env knobs (read at schedule build / pipeline compile time):

* ``RS_XOR_CSE=0`` — disable Paar CSE (naive per-row term lists; larger
  executables, occasionally a hair faster on XLA CPU).
* ``RS_XOR_MAX_TERMS`` — refuse to build schedules whose naive term
  count exceeds this (default 32768): compile time scales with the term
  count, and a pathological (k, rows, w) combination should fail with an
  actionable error instead of hanging the build.
* ``RS_SCHEDULE_STORE`` — ``0``/``off`` disables schedule persistence,
  a path overrides the default (the ``RS_RUNLOG`` ledger).
* ``RS_XOR_PACK_REUSE=0`` — disable packed-operand reuse (callers fall
  back to per-dispatch packing; A/B escape hatch).
* ``RS_XOR_OPT=0`` — disable the schedule-optimizer pass
  (ops/xor_opt.py: demand-driven node reordering, access-pattern term
  grouping, chain region tiling, unpack splitting — byte-identical
  output either way, the pass only rewrites emission).
* ``RS_XOR_TILE`` / ``RS_XOR_TILE_BUDGET`` — force the chain tile
  width in packed words (0 = untiled) / set the cache budget the auto
  tile choice targets (default 2 MiB).  See ops/xor_opt.py.
"""

from __future__ import annotations

import functools
import hashlib
import json
import os
import socket
import threading
import time
from dataclasses import dataclass, replace

import numpy as np

from .gf import get_field
from ..obs import metrics as _metrics, profiler as _prof

__all__ = [
    "XorSchedule", "XorPipeline", "PackedOperand", "build_schedule",
    "matrix_digest", "gf_matmul_xor", "get_pipeline",
    "clear_pipeline_cache", "schedule_stats", "pipeline_stats",
    "pack_operand", "pack_reuse_enabled", "pack_timing_enabled",
    "store_stats",
]

_SUPPORTED_W = (8, 16)

# Symbol columns are padded up to a multiple of 32 so every row's byte
# stream splits into whole 8-byte SWAR blocks grouped in quads.
_COL_ALIGN = 32


def _max_terms() -> int:
    try:
        v = int(os.environ.get("RS_XOR_MAX_TERMS", "32768"))
        return v if v > 0 else 32768
    except ValueError:
        return 32768


def _cse_enabled() -> bool:
    return os.environ.get("RS_XOR_CSE", "1").lower() not in (
        "0", "false", "off", "no"
    )


def pack_reuse_enabled() -> bool:
    """Whether chained consumers may share a :class:`PackedOperand`
    (RS_XOR_PACK_REUSE, default on; read per call so tests/A-B toggle)."""
    return os.environ.get("RS_XOR_PACK_REUSE", "1").lower() not in (
        "0", "false", "off", "no"
    )


# -- binary-matrix lowering (host) -------------------------------------------


def binary_matrix(A: np.ndarray, w: int) -> np.ndarray:
    """(rows, k) GF(2^w) matrix -> (rows*w, k*w) uint8 0/1 operator.

    Block (ri, ki) is ``gf.bitmatrix(A[ri, ki])``: bits(c*b) = M_c @
    bits(b) over GF(2).  Built per distinct value so w=16 never
    materialises the full 16 MB ``gf.bitmats`` table for a handful of
    coefficients.
    """
    gf = get_field(w)
    A = np.asarray(A)
    rows, k = A.shape
    mats = {int(v): gf.bitmatrix(int(v)) for v in np.unique(A)}
    blocks = np.empty((rows, k, w, w), dtype=np.uint8)
    for ri in range(rows):
        for ki in range(k):
            blocks[ri, ki] = mats[int(A[ri, ki])]
    return blocks.transpose(0, 2, 1, 3).reshape(rows * w, k * w)


def matrix_digest(A, w: int) -> str:
    """Stable identity of a coefficient matrix for schedule/plan keying."""
    A = np.ascontiguousarray(np.asarray(A))
    h = hashlib.blake2b(digest_size=8)
    h.update(f"{w}:{A.shape[0]}x{A.shape[1]}:{A.dtype.str}".encode())
    h.update(A.tobytes())
    return h.hexdigest()


# -- greedy pair-frequency CSE (Paar) ----------------------------------------


def paar_cse(rows: list[set[int]], n_inputs: int):
    """Greedy pair-frequency elimination over the binary-matrix rows.

    Repeatedly finds the column pair co-occurring in the most rows and
    rewrites it into a fresh node (one shared XOR), until no pair occurs
    twice — Paar's classic XOR-count minimisation.  Incremental: the
    symmetric co-occurrence matrix grows geometrically and only the
    touched rows' outer products move per step, with a per-column
    row-index map so a step visits exactly the rows it rewrites —
    decode-sized matrices (256x256) schedule in well under a second.

    Returns ``(pair_ops, rows)`` where ``pair_ops[t] = (a, b)`` defines
    node ``n_inputs + t`` and ``rows`` holds each output's remaining
    term sets (referencing inputs and nodes).
    """
    cap = max(16, 2 * n_inputs)
    co = np.zeros((cap, cap), dtype=np.int32)
    rows_with: dict[int, set[int]] = {}
    for ri, s in enumerate(rows):
        idx = np.fromiter(s, dtype=np.int64, count=len(s))
        co[np.ix_(idx, idx)] += 1
        for c in s:
            rows_with.setdefault(c, set()).add(ri)
    n = n_inputs
    pair_ops: list[tuple[int, int]] = []
    while True:
        live = co[:n, :n]
        np.fill_diagonal(live, 0)  # self-pairs from the outer updates
        flat = int(np.argmax(live))
        a, b = flat // n, flat % n
        if live[a, b] < 2:
            break
        if a > b:
            a, b = b, a
        if n == cap:
            grown = np.zeros((2 * cap, 2 * cap), dtype=np.int32)
            grown[:cap, :cap] = co
            co, cap = grown, 2 * cap
        for ri in list(rows_with[a] & rows_with[b]):
            s = rows[ri]
            idx = np.fromiter(s, dtype=np.int64, count=len(s))
            co[np.ix_(idx, idx)] -= 1
            s.discard(a)
            s.discard(b)
            s.add(n)
            rows_with[a].discard(ri)
            rows_with[b].discard(ri)
            rows_with.setdefault(n, set()).add(ri)
            idx = np.fromiter(s, dtype=np.int64, count=len(s))
            co[np.ix_(idx, idx)] += 1
        pair_ops.append((int(a), int(b)))
        n += 1
    return pair_ops, rows


# -- schedule ----------------------------------------------------------------


@dataclass(frozen=True)
class XorSchedule:
    """One lowered+scheduled coefficient matrix (hashable, immutable).

    ``pair_ops`` are the CSE nodes (node ``n_inputs + t`` = XOR of the
    two referenced nodes); ``rows`` lists each output plane's term nodes
    (empty tuple -> the output plane is zero).
    """

    digest: str
    w: int
    rows_out: int
    k: int
    n_inputs: int
    pair_ops: tuple[tuple[int, int], ...]
    rows: tuple[tuple[int, ...], ...]
    terms_naive: int
    terms_cse: int
    cse: bool
    build_seconds: float

    @property
    def xors(self) -> int:
        """XOR ops one dispatch evaluates (per packed word column)."""
        return len(self.pair_ops) + sum(
            max(0, len(r) - 1) for r in self.rows
        )


_SCHEDULE_CACHE: dict[tuple, XorSchedule] = {}
_SCHEDULE_LOCK = threading.Lock()


# -- persistent schedule store (docs/XOR.md "The persistent store") ----------
#
# Schedules are pure data — a deterministic function of (matrix digest,
# cse flag, algorithm version) — so persisting them is safe across
# processes and PLAN_CACHE.clear(): unlike the pipeline/plan caches
# (which pin executables XLA may have evicted), a reloaded schedule is
# byte-identical to a rebuilt one.  Every load re-validates shape fields,
# node-index bounds and the payload checksum, so a torn ledger line or a
# foreign record recomputes instead of crashing or mis-scheduling.

# Bumped when the lowering/CSE/optimizer output contract changes.  v2:
# the schedule-optimizer pass (ops/xor_opt.py) landed — stored payloads
# are still the CANONICAL post-CSE program (the optimizer rewrites at
# pipeline-emission time, so one stored schedule serves RS_XOR_OPT on
# AND off), but records now carry an explicit ``algo_version`` field and
# loads check it FIRST: a record written before the optimizer existed
# must recompute even if its payload digest validates, never be trusted
# to match the current emission contract.
_STORE_ALGO = 2

_STORE_LOCK = threading.Lock()
_STORE_INDEX: dict[tuple, dict] | None = None  # (digest, cse) -> record
# ``built`` counts real Paar-CSE computations this process ran (store on
# or off) — the CI warm-start validator asserts a second process against
# a warm store builds ZERO.
_STORE_STATS = {"hits": 0, "misses": 0, "stored": 0, "corrupt": 0,
                "built": 0}


def _store_path() -> str | None:
    from ..obs import runlog as _runlog

    return _runlog.store_path()


def _count_store(outcome: str) -> None:
    _metrics.counter(
        "rs_schedule_store_total",
        "persistent XOR-schedule store lookups by outcome",
    ).labels(outcome=outcome).inc()


def _rec_ts(rec: dict) -> float:
    try:
        return float(rec.get("ts") or 0.0)
    except (TypeError, ValueError):
        return 0.0


def _store_index() -> dict[tuple, dict]:
    """Lazy-loaded (digest, cse) -> record index of the store file.
    The NEWEST timestamp wins, not file order: rotation carries old
    records forward and may interleave them after concurrent fresh
    appends, so position in the file proves nothing about recency."""
    global _STORE_INDEX
    with _STORE_LOCK:
        if _STORE_INDEX is not None:
            return _STORE_INDEX
    p = _store_path()
    idx: dict[tuple, dict] = {}
    if p:
        from ..obs import runlog as _runlog

        for rec in _runlog.read_records(p):
            if rec.get("kind") != "rs_xor_schedule":
                continue
            digest = rec.get("digest")
            if not isinstance(digest, str):
                continue
            key = (digest, bool(rec.get("cse")))
            cur = idx.get(key)
            if cur is None or _rec_ts(rec) >= _rec_ts(cur):
                idx[key] = rec
    with _STORE_LOCK:
        if _STORE_INDEX is None:
            _STORE_INDEX = idx
        return _STORE_INDEX


def _reset_store_index() -> None:
    """Forget the loaded index (next lookup re-reads the store file) —
    paired with cache clears so a clear can never serve an index that
    predates concurrent writers, and tests can re-point the store env."""
    global _STORE_INDEX
    with _STORE_LOCK:
        _STORE_INDEX = None


def _payload_digest(pair_ops, rows) -> str:
    h = hashlib.blake2b(digest_size=8)
    payload = [
        [[int(a), int(b)] for a, b in pair_ops],
        [[int(t) for t in r] for r in rows],
    ]
    h.update(json.dumps(payload, separators=(",", ":")).encode())
    return h.hexdigest()


def _schedule_from_store(digest: str, cse: bool, A: np.ndarray,
                         w: int) -> XorSchedule | None:
    """Validated store load for one (digest, cse); None on miss or on any
    corruption (counted ``corrupt`` — the caller recomputes)."""
    if not _store_path():
        return None
    rec = _store_index().get((digest, cse))
    if rec is None:
        with _STORE_LOCK:
            _STORE_STATS["misses"] += 1
        _count_store("miss")
        return None
    try:
        # Explicit algorithm-version gate, checked before anything else:
        # pre-optimizer records (algo_version absent or < 2) carry a
        # payload whose digest may well validate — digest proves the
        # record is intact, not that it matches the current emission
        # contract — so the version field is authoritative on its own.
        if rec.get("algo_version") != _STORE_ALGO:
            raise ValueError("algorithm version mismatch")
        if rec.get("algo") != _STORE_ALGO:
            raise ValueError("legacy algo field disagrees")
        rows_out, k = int(rec["rows_out"]), int(rec["k"])
        n_inputs = int(rec["n_inputs"])
        if (int(rec["w"]), rows_out, k) != (w, A.shape[0], A.shape[1]):
            raise ValueError("shape fields disagree with the matrix")
        if n_inputs != k * w:
            raise ValueError("n_inputs inconsistent with (k, w)")
        pair_ops = tuple(
            (int(a), int(b)) for a, b in rec["pair_ops"]
        )
        rows = tuple(tuple(int(t) for t in r) for r in rec["rows"])
        if len(rows) != rows_out * w:
            raise ValueError("row count inconsistent with (rows_out, w)")
        for t, (a, b) in enumerate(pair_ops):
            if not (0 <= a < n_inputs + t and 0 <= b < n_inputs + t):
                raise ValueError("pair op references an undefined node")
        n_nodes = n_inputs + len(pair_ops)
        for r in rows:
            for t in r:
                if not 0 <= t < n_nodes:
                    raise ValueError("row term references an undefined node")
        if rec.get("payload_digest") != _payload_digest(pair_ops, rows):
            raise ValueError("payload checksum mismatch")
        sched = XorSchedule(
            digest=digest, w=w, rows_out=rows_out, k=k, n_inputs=n_inputs,
            pair_ops=pair_ops, rows=rows,
            terms_naive=int(rec["terms_naive"]),
            terms_cse=int(rec["terms_cse"]),
            cse=cse, build_seconds=0.0,
        )
    except Exception:
        # Torn line, foreign writer, stale algorithm — recompute (and
        # re-store, superseding the bad record).  Never crash, never
        # trust unvalidated XOR terms.
        with _STORE_LOCK:
            if _STORE_INDEX is not None:
                # Forget the bad record so the recompute's store append
                # is not skipped as "already present".
                _STORE_INDEX.pop((digest, cse), None)
            _STORE_STATS["corrupt"] += 1
        _count_store("corrupt")
        return None
    with _STORE_LOCK:
        _STORE_STATS["hits"] += 1
    _count_store("hit")
    return sched


def _schedule_to_store(sched: XorSchedule) -> None:
    """Best-effort append of a freshly built schedule (no-op when the
    store is disabled or the record is already present)."""
    p = _store_path()
    if not p:
        return
    key = (sched.digest, sched.cse)
    idx = _store_index()
    if key in idx:
        return
    from ..obs import runlog as _runlog

    rec = {
        "kind": "rs_xor_schedule",
        "schema": _runlog.SCHEMA_VERSION,
        "algo": _STORE_ALGO,
        "algo_version": _STORE_ALGO,
        "digest": sched.digest,
        "cse": sched.cse,
        "w": sched.w,
        "rows_out": sched.rows_out,
        "k": sched.k,
        "n_inputs": sched.n_inputs,
        "pair_ops": [list(p_) for p_ in sched.pair_ops],
        "rows": [list(r) for r in sched.rows],
        "payload_digest": _payload_digest(sched.pair_ops, sched.rows),
        "terms_naive": sched.terms_naive,
        "terms_cse": sched.terms_cse,
        "build_seconds": round(sched.build_seconds, 6),
        "ts": time.time(),
        "run": _runlog.run_id(),
        "host": socket.gethostname(),
    }
    _runlog.append(rec, p)
    with _STORE_LOCK:
        if _STORE_INDEX is not None:
            _STORE_INDEX[key] = rec
        _STORE_STATS["stored"] += 1
    _count_store("stored")


def store_stats(load: bool = False) -> dict:
    """Persistent-store facts for `rs doctor` / daemon stats: resolved
    path, entry count (``load=True`` forces the index read; otherwise
    only a previously loaded index is counted) and this process's
    hit/miss/stored/corrupt tallies."""
    p = _store_path()
    if load and p:
        _store_index()
    with _STORE_LOCK:
        entries = (
            len(_STORE_INDEX) if _STORE_INDEX is not None else None
        )
        out = dict(_STORE_STATS)
    out.update({"path": p, "enabled": p is not None, "entries": entries})
    return out


def build_schedule(A, w: int, cse: bool | None = None) -> XorSchedule:
    """Lower ``A`` to GF(2) and CSE-schedule it — cached by digest
    in-process, then by the persistent store, then computed (and stored
    so the next process skips the Paar pass)."""
    if w not in _SUPPORTED_W:
        raise ValueError(
            f"strategy='xor' supports w in {_SUPPORTED_W}, got w={w}"
        )
    if cse is None:
        cse = _cse_enabled()
    A = np.asarray(A)
    digest = matrix_digest(A, w)
    key = (digest, bool(cse))
    with _SCHEDULE_LOCK:
        hit = _SCHEDULE_CACHE.get(key)
    if hit is not None:
        _prof.attr(schedule="memory")
        return hit
    loaded = _schedule_from_store(digest, bool(cse), A, w)
    if loaded is not None:
        _prof.attr(schedule="store")
        with _SCHEDULE_LOCK:
            return _SCHEDULE_CACHE.setdefault(key, loaded)
    _prof.attr(schedule="built")
    with _STORE_LOCK:
        _STORE_STATS["built"] += 1
    t0 = time.perf_counter()
    abin = binary_matrix(A, w)
    naive = int(abin.sum())
    limit = _max_terms()
    if naive > limit:
        raise ValueError(
            f"xor schedule for {A.shape[0]}x{A.shape[1]} w={w} needs "
            f"{naive} XOR terms, over RS_XOR_MAX_TERMS={limit}; use "
            "strategy='bitplane' (or raise the knob) for matrices this "
            "large"
        )
    row_sets = [set(np.nonzero(r)[0]) for r in abin]
    if cse:
        pair_ops, row_sets = paar_cse(row_sets, abin.shape[1])
    else:
        pair_ops = []
    sched = XorSchedule(
        digest=digest,
        w=w,
        rows_out=A.shape[0],
        k=A.shape[1],
        n_inputs=abin.shape[1],
        pair_ops=tuple(pair_ops),
        rows=tuple(tuple(int(t) for t in sorted(s)) for s in row_sets),
        terms_naive=naive,
        terms_cse=len(pair_ops) + sum(len(s) for s in row_sets),
        cse=bool(cse),
        build_seconds=time.perf_counter() - t0,
    )
    _schedule_to_store(sched)
    with _SCHEDULE_LOCK:
        return _SCHEDULE_CACHE.setdefault(key, sched)


def schedule_stats() -> list[dict]:
    """Built schedules (digest, shape, term counts before/after CSE) —
    the `rs doctor` surface that makes plan-cache bloat visible."""
    with _SCHEDULE_LOCK:
        scheds = list(_SCHEDULE_CACHE.values())
    return [
        {
            "digest": s.digest,
            "w": s.w,
            "rows_out": s.rows_out,
            "k": s.k,
            "cse": s.cse,
            "terms_naive": s.terms_naive,
            "terms_cse": s.terms_cse,
            "xors": s.xors,
            "build_seconds": round(s.build_seconds, 6),
        }
        for s in scheds
    ]


# -- packed bit-plane transforms (traced) ------------------------------------
#
# All constants/widths below are the little-endian uint32 formulation;
# the SWAR pair transpose maps virtual-block bit (i, j) to lane
# (j+4) % 8, bit (i+4) % 8 — an involution, verified exhaustively in
# tests/test_xor_gemm.py.

_PLANE_LANE = tuple((j + 4) % 8 for j in range(8))


def _u32(v):
    import jax.numpy as jnp

    return jnp.uint32(v)


def _dswap(x, mask, shift):
    t = (x ^ (x >> shift)) & mask
    return x ^ t ^ (t << shift)


def _swar_pair(x, y):
    """8x8 bit transpose of virtual blocks (x[t] bytes 0-3, y[t] 4-7)."""
    m1, m2 = _u32(0x00AA00AA), _u32(0x0000CCCC)
    hi, lo = _u32(0xF0F0F0F0), _u32(0x0F0F0F0F)
    x = _dswap(x, m1, 7)
    y = _dswap(y, m1, 7)
    x = _dswap(x, m2, 14)
    y = _dswap(y, m2, 14)
    t = (x & hi) | ((y >> 4) & lo)
    y = ((x << 4) & hi) | (y & lo)
    return t, y


def _t4x4(x0, x1, x2, x3):
    """4x4 byte transpose across four uint32 streams (shift/mask only)."""
    low16, hi16 = _u32(0x0000FFFF), _u32(0xFFFF0000)
    ev, od = _u32(0x00FF00FF), _u32(0xFF00FF00)
    t0 = (x0 & low16) | (x2 << 16)
    t1 = (x1 & low16) | (x3 << 16)
    t2 = (x0 >> 16) | (x2 & hi16)
    t3 = (x1 >> 16) | (x3 & hi16)
    u0 = (t0 & ev) | ((t1 & ev) << 8)
    u1 = ((t0 >> 8) & ev) | (t1 & od)
    u2 = (t2 & ev) | ((t3 & ev) << 8)
    u3 = ((t2 >> 8) & ev) | (t3 & od)
    return u0, u1, u2, u3


def _split(a, n):
    step = a.shape[0] // n
    return [a[i * step:(i + 1) * step] for i in range(n)]


def _pack_words(w):
    """(nw4,) uint32 of raw bytes -> tuple of 8 (nw4//8,) plane words.

    Contiguous half/quarter pairing: virtual block t = (first-half word
    t, second-half word t); quads likewise.  Planes come back indexed by
    TRUE bit number via the lane permutation.
    """
    xh, yh = _split(w, 2)
    x, y = _swar_pair(xh, yh)
    lanes = list(_t4x4(*_split(x, 4))) + list(_t4x4(*_split(y, 4)))
    return tuple(lanes[_PLANE_LANE[j]] for j in range(8))


def _unpack_words(planes):
    """Inverse of :func:`_pack_words`, returned as 8 contiguous pieces
    (concatenate in order to recover the raw byte words)."""
    lanes = [None] * 8
    for j in range(8):
        lanes[_PLANE_LANE[j]] = planes[j]
    xs = _t4x4(*lanes[:4])
    ys = _t4x4(*lanes[4:])
    xps, yps = [], []
    for s in range(4):
        a, b = _swar_pair(xs[s], ys[s])
        xps.append(a)
        yps.append(b)
    return xps + yps


_LOBYTES = 0x00FF00FF


def _pack_row(row, w: int):
    """One data row -> tuple of ``w`` packed plane vectors."""
    import jax.numpy as jnp
    from jax import lax

    if w == 8:
        words = lax.bitcast_convert_type(row.reshape(-1, 4), jnp.uint32)
        return _pack_words(words)
    # w == 16, little-endian symbols: split lo/hi byte streams with
    # shift/mask compaction (contiguous-half pairing again), then run
    # the byte machinery per stream — planes 0-7 from lo, 8-15 from hi.
    m = _u32(_LOBYTES)
    words = lax.bitcast_convert_type(row.reshape(-1, 2), jnp.uint32)
    lo_sp, hi_sp = words & m, (words >> 8) & m
    lo_a, lo_b = _split(lo_sp, 2)
    hi_a, hi_b = _split(hi_sp, 2)
    lo = lo_a | (lo_b << 8)
    hi = hi_a | (hi_b << 8)
    return _pack_words(lo) + _pack_words(hi)


def _unpack_row_pieces(planes, w: int):
    """Output planes of one row -> contiguous uint32 pieces, in order."""
    if w == 8:
        return _unpack_words(planes)
    m = _u32(_LOBYTES)
    lo_ps = _unpack_words(planes[:8])
    hi_ps = _unpack_words(planes[8:])
    first = [
        (lp & m) | ((hp & m) << 8) for lp, hp in zip(lo_ps, hi_ps)
    ]
    second = [
        ((lp >> 8) & m) | ((hp & ~m))
        for lp, hp in zip(lo_ps, hi_ps)
    ]
    return first + second


def _xor_tree(xs):
    while len(xs) > 1:
        xs = [
            xs[i] ^ xs[i + 1] if i + 1 < len(xs) else xs[i]
            for i in range(0, len(xs), 2)
        ]
    return xs[0]


# -- the three stage programs ------------------------------------------------


def _pack_stage(B, w: int):
    k = B.shape[0]
    out = []
    for i in range(k):
        out.extend(_pack_row(B[i], w))
    return tuple(out)


def _chain_stage(nodes, schedule: XorSchedule):
    import jax.numpy as jnp

    nodes = list(nodes)
    for a, b in schedule.pair_ops:
        nodes.append(nodes[a] ^ nodes[b])
    return tuple(
        _xor_tree([nodes[t] for t in terms]) if terms
        else jnp.zeros_like(nodes[0])
        for terms in schedule.rows
    )


def _tiled_chain_stage(nodes, schedule: XorSchedule, tile: int):
    """The chain as a ``lax.scan`` over contiguous column tiles of the
    plane vectors (ops/xor_opt.py "region tiling"): per step every input
    plane is sliced to ``tile`` words, the whole XOR program runs on the
    slices — live set sized to the cache budget — and the outputs are
    written back at the tile offset.  A non-dividing remainder runs as
    one static tail after the scan.  Byte-identical to
    :func:`_chain_stage` (same program, blocked evaluation)."""
    import jax.numpy as jnp
    from jax import lax

    nodes = tuple(nodes)
    nw = nodes[0].shape[0]
    nt, tail = nw // tile, nw % tile

    def _block(sl):
        return _chain_stage(sl, schedule)

    def step(carry, t):
        off = t * tile
        sl = tuple(
            lax.dynamic_slice(p_, (off,), (tile,)) for p_ in nodes
        )
        outs = _block(sl)
        carry = tuple(
            lax.dynamic_update_slice(c, o, (off,))
            for c, o in zip(carry, outs)
        )
        return carry, None

    init = tuple(
        jnp.zeros((nw,), nodes[0].dtype) for _ in schedule.rows
    )
    out, _ = lax.scan(step, init, jnp.arange(nt))
    if tail:
        sl = tuple(p_[nt * tile:] for p_ in nodes)
        outs = _block(sl)
        out = tuple(
            lax.dynamic_update_slice(c, o, (nt * tile,))
            for c, o in zip(out, outs)
        )
    return out


def _pieces_stage(outs, w: int, rows_out: int):
    """Unpack's SWAR half only: output planes -> contiguous uint32
    pieces (row-major, in concatenation order) with NO assembly.  Kept
    in its own executable when the optimizer splits the unpack — fused
    with the concatenate, XLA CPU re-runs the transform per concatenate
    operand (see ops/xor_opt.py)."""
    pieces = []
    for ri in range(rows_out):
        pieces.extend(_unpack_row_pieces(outs[ri * w:(ri + 1) * w], w))
    return tuple(pieces)


def _assemble_stage(pieces, w: int, rows_out: int, cols: int):
    """Unpack's assembly half: concatenate the materialised pieces and
    bitcast back to symbols."""
    import jax.numpy as jnp
    from jax import lax

    words = jnp.concatenate(list(pieces))
    if w == 8:
        return lax.bitcast_convert_type(words, jnp.uint8).reshape(
            rows_out, cols
        )
    return lax.bitcast_convert_type(words, jnp.uint16).reshape(
        rows_out, cols
    )


def _unpack_stage(outs, w: int, rows_out: int, cols: int):
    return _assemble_stage(
        _pieces_stage(outs, w, rows_out), w, rows_out, cols
    )


# -- shared stage executables -------------------------------------------------
#
# pack/unpack are pure layout transforms: they depend on the operand
# class (rows, cols, dtype, w) but NOT on the schedule, so they compile
# once per class and every pipeline of that class shares them — decode
# survivor-set churn compiles one chain per subset, not three stages.

_STAGE_CACHE: dict[tuple, object] = {}
_STAGE_LOCK = threading.Lock()


def _plane_struct(cols: int):
    import jax

    return jax.ShapeDtypeStruct((cols // _COL_ALIGN,), np.uint32)


def _pack_exe(rows: int, cols: int, dtype, w: int):
    """Compiled pack stage for one (rows, cols, dtype, w) operand class."""
    import jax

    key = ("pack", rows, cols, np.dtype(dtype).str, w)
    with _STAGE_LOCK:
        hit = _STAGE_CACHE.get(key)
    if hit is not None:
        return hit
    exe = (
        jax.jit(lambda b: _pack_stage(b, w))
        .lower(jax.ShapeDtypeStruct((rows, cols), np.dtype(dtype)))
        .compile()
    )
    with _STAGE_LOCK:
        return _STAGE_CACHE.setdefault(key, exe)


def _unpack_exe(rows_out: int, cols: int, w: int):
    """Compiled unpack stage for one (rows_out, cols, w) output class."""
    import jax

    key = ("unpack", rows_out, cols, w)
    with _STAGE_LOCK:
        hit = _STAGE_CACHE.get(key)
    if hit is not None:
        return hit
    outs_struct = tuple([_plane_struct(cols)] * (rows_out * w))
    exe = (
        jax.jit(lambda os_: _unpack_stage(os_, w, rows_out, cols))
        .lower(outs_struct)
        .compile()
    )
    with _STAGE_LOCK:
        return _STAGE_CACHE.setdefault(key, exe)


def _pieces_exe(rows_out: int, cols: int, w: int):
    """Compiled SWAR half of a split unpack (ops/xor_opt.py)."""
    import jax

    key = ("pieces", rows_out, cols, w)
    with _STAGE_LOCK:
        hit = _STAGE_CACHE.get(key)
    if hit is not None:
        return hit
    outs_struct = tuple([_plane_struct(cols)] * (rows_out * w))
    exe = (
        jax.jit(lambda os_: _pieces_stage(os_, w, rows_out))
        .lower(outs_struct)
        .compile()
    )
    with _STAGE_LOCK:
        return _STAGE_CACHE.setdefault(key, exe)


def _assemble_exe(rows_out: int, cols: int, w: int):
    """Compiled assembly half of a split unpack (ops/xor_opt.py)."""
    import jax

    key = ("assemble", rows_out, cols, w)
    with _STAGE_LOCK:
        hit = _STAGE_CACHE.get(key)
    if hit is not None:
        return hit
    pieces_struct = tuple([_plane_struct(cols)] * (rows_out * w))
    exe = (
        jax.jit(lambda ps: _assemble_stage(ps, w, rows_out, cols))
        .lower(pieces_struct)
        .compile()
    )
    with _STAGE_LOCK:
        return _STAGE_CACHE.setdefault(key, exe)


def pack_timing_enabled() -> bool:
    """Whether pack-stage walls are recorded (``RS_XOR_PACK_TIMING=1``
    AND metrics on).  Opt-in on top of RS_METRICS because the timing
    must BLOCK on the planes: a production deployment scraping metrics
    would otherwise lose the async pack->chain overlap on EVERY xor
    dispatch, not just the ones being measured."""
    return _metrics.enabled() and os.environ.get(
        "RS_XOR_PACK_TIMING", "0"
    ).lower() in ("1", "true", "on", "yes")


def _observed_pack(exe, B):
    """Run a pack executable, timing its wall into ``rs_xor_pack_seconds``
    when pack timing is opted in (see :func:`pack_timing_enabled`).  The
    default path — timing off — is the plain async dispatch and costs
    nothing."""
    if not pack_timing_enabled():
        return exe(B)
    import jax

    t0 = time.perf_counter()
    planes = exe(B)
    jax.block_until_ready(planes)
    _metrics.quantile(
        "rs_xor_pack_seconds",
        "xor pack-stage wall seconds (streaming quantiles)",
    ).observe(time.perf_counter() - t0)
    return planes


def _count_pack_reuse(outcome: str) -> None:
    _metrics.counter(
        "rs_xor_pack_reuse_total",
        "xor pack-stage executions vs packed-operand reuses",
    ).labels(outcome=outcome).inc()


class PackedOperand:
    """A ``B`` operand already in the packed bit-plane domain.

    The warm-path handle (docs/XOR.md "Packed-operand reuse"): chained
    xor dispatches that consume the same staged segment — locate
    decode's syndrome GEMM then its recovery GEMM — pack it ONCE and
    thread this handle through ``codec``/``plan``; the second consumer
    skips ``_pack_stage`` entirely.  ``planes`` is the row-major tuple
    of ``rows * w`` plane vectors; :meth:`select` restricts to a row
    subset (pure tuple slicing — planes are per-row, so a row subset is
    a plane subset).  ``cols_true``/``cap`` carry the plan-layer
    bookkeeping of the staged segment the planes came from.
    """

    __slots__ = ("planes", "rows", "cols", "w", "dtype", "cols_true",
                 "cap")

    def __init__(self, planes, rows: int, cols: int, w: int, dtype,
                 cols_true: int | None = None, cap: int | None = None):
        self.planes = tuple(planes)
        self.rows = int(rows)
        self.cols = int(cols)
        self.w = int(w)
        self.dtype = np.dtype(dtype)
        self.cols_true = int(cols_true) if cols_true is not None else cols
        self.cap = cap

    @property
    def shape(self):
        return (self.rows, self.cols)

    def select(self, row_positions) -> "PackedOperand":
        """Packed view of a row subset, in the given order."""
        w = self.w
        planes: list = []
        for r in row_positions:
            r = int(r)
            if not 0 <= r < self.rows:
                raise ValueError(
                    f"row {r} out of range for packed operand of "
                    f"{self.rows} rows"
                )
            planes.extend(self.planes[r * w:(r + 1) * w])
        return PackedOperand(
            planes, len(planes) // w, self.cols, w, self.dtype,
            cols_true=self.cols_true, cap=self.cap,
        )


def pack_operand(B, w: int, *, cols_true: int | None = None,
                 cap: int | None = None) -> PackedOperand:
    """Pack a concrete (rows, cols) symbol array once for reuse across
    chained dispatches.  ``cols`` must already be 32-aligned (the plan
    layer's staged segments are; use :func:`padded_cols` otherwise)."""
    rows, cols = B.shape
    if cols % _COL_ALIGN:
        raise ValueError(
            f"packed operand cols must be {_COL_ALIGN}-aligned, got {cols}"
        )
    exe = _pack_exe(rows, cols, B.dtype, w)
    planes = _observed_pack(exe, B)
    _count_pack_reuse("packed")
    return PackedOperand(
        planes, rows, cols, w, B.dtype, cols_true=cols_true, cap=cap
    )


# -- compiled pipeline -------------------------------------------------------


class XorPipeline:
    """Three AOT executables for one (schedule, k, padded-cols, dtype).

    Callable with the plan layer's ``(A, B)`` convention — ``A`` is
    ignored (its values are baked into the schedule; the plan key
    carries its digest).  ``B`` must already be padded to ``cols``.
    """

    __slots__ = (
        "schedule", "k", "cols", "dtype", "compile_seconds",
        "cost_analysis", "calls", "opt", "_pack", "_chain", "_unpack",
        "_pieces", "_assemble",
    )

    def __init__(self, schedule: XorSchedule, k: int, cols: int, dtype):
        import jax

        from . import xor_opt as _xopt

        if cols % _COL_ALIGN:
            raise ValueError(
                f"xor pipeline cols must be {_COL_ALIGN}-aligned, "
                f"got {cols}"
            )
        self.schedule = schedule
        self.k = k
        self.cols = cols
        self.dtype = np.dtype(dtype)
        self.calls = 0
        t0 = time.perf_counter()
        w = schedule.w
        # pack/unpack come from the shared per-class stage cache (they
        # are schedule-independent); only the chain is compiled per
        # schedule.  One plane vector holds one bit of every symbol
        # column: cols/32 packed uint32 words for BOTH widths (w=16
        # splits into lo/hi byte streams first, doubling the plane
        # count, not their size).
        #
        # The optimizer pass (ops/xor_opt.py, RS_XOR_OPT) rewrites the
        # EMITTED program only: ``schedule`` stays the canonical stored
        # form, ``emit`` is what the chain executable is traced from.
        # Outputs are byte-identical either way.
        emit = schedule
        n_planes = (
            schedule.n_inputs + len(schedule.pair_ops)
            + len(schedule.rows)
        )
        nw = cols // _COL_ALIGN
        if _xopt.opt_enabled():
            pair_ops, rows, moved, groups = _xopt.optimize_program(
                schedule.pair_ops, schedule.rows, schedule.n_inputs
            )
            emit = replace(schedule, pair_ops=pair_ops, rows=rows)
            tile, n_tiles, ws = _xopt.choose_tile(n_planes, nw)
            self.opt = _xopt.OptStats(
                enabled=True, nodes_moved=moved, term_groups=groups,
                tile_words=tile, n_tiles=n_tiles,
                est_working_set_bytes=ws,
                split_unpack=_xopt.split_unpack(nw),
            )
        else:
            self.opt = _xopt.disabled_stats()
        self._pack = _pack_exe(k, cols, self.dtype, w)
        nodes_struct = tuple([_plane_struct(cols)] * (k * w))
        tile = self.opt.tile_words
        chain_fn = (
            (lambda ns: _tiled_chain_stage(ns, emit, tile)) if tile
            else (lambda ns: _chain_stage(ns, emit))
        )
        self._chain = (
            jax.jit(chain_fn).lower(nodes_struct).compile()
        )
        if self.opt.split_unpack:
            self._unpack = None
            self._pieces = _pieces_exe(schedule.rows_out, cols, w)
            self._assemble = _assemble_exe(schedule.rows_out, cols, w)
        else:
            self._unpack = _unpack_exe(schedule.rows_out, cols, w)
            self._pieces = self._assemble = None
        self.compile_seconds = time.perf_counter() - t0
        self.cost_analysis = self._merged_cost()

    def _merged_cost(self):
        from ..obs.attrib import extract_cost_analysis

        stages = (
            (self._pack, self._chain, self._unpack)
            if self._unpack is not None
            else (self._pack, self._chain, self._pieces, self._assemble)
        )
        total: dict = {}
        for exe in stages:
            ca = extract_cost_analysis(exe)
            if not ca:
                return None
            for key, v in ca.items():
                total[key] = total.get(key, 0.0) + v
        return total or None

    def __call__(self, A, B):
        self.calls += 1
        # One thread-local read: with no RS_PROF profile open this call
        # is the unchanged async three-stage dispatch.
        prof = _prof.active()
        if isinstance(B, PackedOperand):
            # Warm path: the operand was packed once by an earlier
            # consumer (docs/XOR.md) — validate the class and skip the
            # pack stage entirely.
            if (B.rows, B.cols, B.w) != (
                self.k, self.cols, self.schedule.w
            ) or B.dtype != self.dtype:
                raise ValueError(
                    f"packed operand ({B.rows}x{B.cols}, w={B.w}, "
                    f"{B.dtype}) does not match pipeline "
                    f"({self.k}x{self.cols}, w={self.schedule.w}, "
                    f"{self.dtype})"
                )
            _count_pack_reuse("reused")
            if prof is not None:
                _prof.attr(pack="reused")
            planes = B.planes
        else:
            # Pipeline-internal packs count too: the packed-vs-reused
            # comparison is only meaningful if EVERY pack execution
            # lands in the "packed" bucket, including the fallback
            # re-packs after a located correction drops its handle.
            _count_pack_reuse("packed")
            if prof is None:
                planes = _observed_pack(self._pack, B)
            else:
                _prof.attr(pack="packed")
                planes = _prof.run_stage("pack", self._pack, B)
        if prof is None:
            outs = self._chain(planes)
            if self._unpack is not None:
                return self._unpack(outs)
            return self._assemble(self._pieces(outs))
        # Profiled dispatch: each stage blocked and timed (the overlap
        # this collapses is exactly why RS_PROF is opt-in + sampled).
        # pieces+assemble is ONE unpack stage — the split is an
        # optimizer working-set choice, not a pipeline stage.
        outs = _prof.run_stage("chain", self._chain, planes)
        if self._unpack is not None:
            return _prof.run_stage("unpack", self._unpack, outs)
        return _prof.run_stage(
            "unpack", lambda o: self._assemble(self._pieces(o)), outs
        )

    def describe(self) -> dict:
        s = self.schedule
        return {
            "digest": s.digest,
            "w": s.w,
            "k": self.k,
            "rows_out": s.rows_out,
            "cols": self.cols,
            "cse": s.cse,
            "terms_naive": s.terms_naive,
            "terms_cse": s.terms_cse,
            "xors": s.xors,
            "calls": self.calls,
            "compile_seconds": round(self.compile_seconds, 6),
            "opt": self.opt.as_dict(),
        }


_PIPELINE_CACHE: dict[tuple, XorPipeline] = {}
_PIPELINE_LOCK = threading.Lock()


def get_pipeline(A, B_shape, B_dtype, w: int) -> XorPipeline:
    """Build-or-fetch the compiled pipeline for concrete coefficients
    ``A`` and a (k, cols) operand class.  ``cols`` must be 32-aligned
    (use :func:`padded_cols`)."""
    from . import xor_opt as _xopt

    schedule = build_schedule(A, w)
    k, cols = B_shape
    # The optimizer fingerprint keys the pipeline too: RS_XOR_OPT (and
    # its tile knobs) change the EMITTED executables, so variants built
    # under different settings must never share a slot (the A/B tool
    # toggles the env between calls and expects both to stay cached).
    key = (
        schedule.digest, schedule.cse, k, cols,
        np.dtype(B_dtype).str, _xopt.env_fingerprint(),
    )
    with _PIPELINE_LOCK:
        pipe = _PIPELINE_CACHE.get(key)
        if pipe is None:
            pipe = _PIPELINE_CACHE[key] = XorPipeline(
                schedule, k, cols, B_dtype
            )
        return pipe


def clear_pipeline_cache() -> None:
    """Drop compiled pipelines, shared stage executables AND schedules
    (paired with plan-cache clears: the executables pin compiles XLA may
    since have evicted).  The persistent store's in-memory INDEX is also
    reset — but not the store file: schedules are pure data (deterministic
    in (digest, cse, algo version)), so a post-clear load re-reads and
    re-validates from disk; it cannot resurrect anything stale, and a
    corrupt entry falls back to recompute (tests/test_warm_path.py pins
    both halves of that contract)."""
    with _PIPELINE_LOCK:
        _PIPELINE_CACHE.clear()
    with _STAGE_LOCK:
        _STAGE_CACHE.clear()
    with _SCHEDULE_LOCK:
        _SCHEDULE_CACHE.clear()
    _reset_store_index()
    # Dependent caches (ring pipelines share the stage cache just
    # cleared, so they must drop with it — registered, not imported, to
    # keep this module free of a ring dependency).
    for hook in list(_CLEAR_HOOKS):
        hook()


_CLEAR_HOOKS: list = []


def register_clear_hook(fn) -> None:
    """Run ``fn`` on every :func:`clear_pipeline_cache` (ring_gemm uses
    this so its pipelines — which share the stage cache — drop too)."""
    if fn not in _CLEAR_HOOKS:
        _CLEAR_HOOKS.append(fn)


def pipeline_stats() -> list[dict]:
    with _PIPELINE_LOCK:
        pipes = list(_PIPELINE_CACHE.values())
    return [p.describe() for p in pipes]


def padded_cols(m: int) -> int:
    """Round a column count up to the pipeline's 32-symbol alignment."""
    return max(_COL_ALIGN, (m + _COL_ALIGN - 1) // _COL_ALIGN * _COL_ALIGN)


def gf_matmul_xor(A, B, w: int = 8):
    """``C = A . B`` over GF(2^w) via the XOR pipeline (eager entry).

    ``A`` must be concrete (its VALUES select the schedule — under a
    ``jit`` trace it would be a tracer, which cannot key a schedule; the
    plan layer passes concrete coefficients by construction).  ``B`` may
    be a device array; ragged widths are zero-padded to the 32-symbol
    alignment and trimmed after (GF linearity makes pad columns zero).
    """
    import jax
    import jax.numpy as jnp

    if isinstance(A, jax.core.Tracer):
        raise TypeError(
            "strategy='xor' needs concrete coefficient values to build "
            "its XOR schedule; call it outside jit (or via the plan "
            "layer), not on a traced A"
        )
    A = np.asarray(A)
    gf = get_field(w)
    dtype = np.dtype(gf.dtype)
    rows_out, k = A.shape
    m = B.shape[1]
    if m == 0:
        return jnp.zeros((rows_out, 0), dtype=dtype)
    cols = padded_cols(m)
    if B.shape[1] != cols:
        B = jnp.asarray(B)
        B = jnp.pad(B, ((0, 0), (0, cols - m)))
    if isinstance(B, jax.core.Tracer):
        # Under a caller's jit the compiled pipeline cannot run; trace
        # the three stage programs inline instead (the schedule is still
        # concrete — only the data is traced).
        schedule = build_schedule(A, w)
        out = _unpack_stage(
            _chain_stage(_pack_stage(B, w), schedule),
            schedule.w, schedule.rows_out, cols,
        )
    else:
        pipe = get_pipeline(A, (k, cols), dtype, w)
        out = pipe(A, B)
    return out[:, :m] if cols != m else out
