"""XOR/ring schedule-optimizer pass — ``RS_XOR_OPT`` (docs/XOR.md).

Post-CSE rewriting of the emitted XOR chains, in the spirit of the
XOR-EC program-optimization literature (arXiv 2108.02692): the Paar
pass minimises the XOR *count*; this pass optimises the *memory
behaviour* of the straight-line program the count is spent in.  Three
transforms, all semantics-preserving (XOR is associative/commutative;
only emission order and blocking change — outputs are byte-identical
with the pass on or off, which CI asserts):

* **Topological reordering** — CSE pair nodes are re-emitted *demand
  driven*: each node right before its first consumer (dependencies
  first), instead of the Paar discovery order.  That minimises the
  def-to-first-use distance, so a node's value is still cache-hot when
  the chain first reads it.  ``nodes_moved`` counts repositioned nodes.
* **Term grouping** — within each output row the XOR terms are grouped
  by memory access pattern: CSE nodes first (most recently produced
  first — the hottest lines), then raw input planes in ascending plane
  order (one contiguous walk of the packed plane block).
* **Region tiling** — the chain executable walks the packed planes in
  contiguous column blocks sized so the whole live set (input planes +
  CSE nodes + output accumulators) of one block fits the cache budget:
  a ``lax.scan`` over column tiles of the plane vectors, slicing every
  input plane and updating every output plane per step.  On the bench
  box this moves the CSE-node traffic from L3 into L2 (measured 7.6 ms
  -> 4.5 ms for the bench chain).  The pack/unpack stages stay whole —
  they are compute-bound layout transforms that touch each word once.

The pass also decides **unpack splitting** (a grouping decision at the
stage level): XLA CPU fuses the unpack's SWAR transform into its final
``concatenate``, which was measured to re-run the transform per
concatenate operand (15.7 ms for an 8 MiB output where the transform
alone costs 2.5 ms).  For large outputs the optimizer emits the SWAR
pieces and the concatenate as two executables (4.2 ms total); small
outputs keep the single executable — an extra dispatch would cost more
than the fusion pathology.

Env knobs (read at pipeline compile time; the pipeline cache key
carries the resolved fingerprint, so toggling mid-process compiles a
separate variant instead of poisoning the cache):

* ``RS_XOR_OPT=0`` — disable the whole pass (legacy emission).
* ``RS_XOR_TILE`` — force the chain tile width in packed words
  (``0`` disables tiling only; unset = auto from the cache budget).
* ``RS_XOR_TILE_BUDGET`` — cache budget in bytes for the auto tile
  choice (default 2 MiB — an L2 of the boxes this was tuned on).
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass

from ..obs import profiler as _prof

__all__ = [
    "OptStats", "opt_enabled", "tile_override", "tile_budget_bytes",
    "reorder_pairs", "group_row_terms", "choose_tile",
    "optimize_program", "split_unpack", "env_fingerprint",
    "UNPACK_SPLIT_MIN_PLANE_BYTES",
]

# Tile bounds (packed uint32 words). 256 words = 1 KiB per plane slice —
# below that the per-tile slice/update overhead beats any locality win.
_MIN_TILE = 256
_MAX_TILE = 1 << 20

# Unpack splitting pays one extra dispatch; worth it only when the
# fused-concatenate pathology costs more. 64 KiB planes (~512 K symbol
# columns at w=8) was comfortably past break-even on the bench box.
UNPACK_SPLIT_MIN_PLANE_BYTES = 65536


def opt_enabled() -> bool:
    """Whether the optimizer pass runs (``RS_XOR_OPT``, default on)."""
    return os.environ.get("RS_XOR_OPT", "1").lower() not in (
        "0", "false", "off", "no"
    )


def tile_override() -> int | None:
    """``RS_XOR_TILE`` as words; ``0`` = force tiling off; None = auto."""
    v = os.environ.get("RS_XOR_TILE")
    if not v:
        return None
    try:
        n = int(v)
    except ValueError:
        return None
    return max(0, n)


def tile_budget_bytes() -> int:
    """Cache budget for the auto tile choice (``RS_XOR_TILE_BUDGET``)."""
    try:
        v = int(os.environ.get("RS_XOR_TILE_BUDGET", str(2 << 20)))
        return v if v > 0 else (2 << 20)
    except ValueError:
        return 2 << 20


def env_fingerprint() -> tuple:
    """Resolved knob state, for pipeline cache keys: two pipelines built
    under different optimizer settings must never share a cache slot."""
    return (opt_enabled(), tile_override(), tile_budget_bytes())


@dataclass(frozen=True)
class OptStats:
    """What the pass did to one pipeline (plan.describe / rs doctor)."""

    enabled: bool
    nodes_moved: int
    term_groups: int
    tile_words: int     # 0 = chain not tiled
    n_tiles: int        # 1 = single whole-width pass
    est_working_set_bytes: int
    split_unpack: bool

    def as_dict(self) -> dict:
        return {
            "enabled": self.enabled,
            "nodes_moved": self.nodes_moved,
            "term_groups": self.term_groups,
            "tile_words": self.tile_words,
            "n_tiles": self.n_tiles,
            "est_working_set_bytes": self.est_working_set_bytes,
            "split_unpack": self.split_unpack,
        }


_DISABLED = OptStats(
    enabled=False, nodes_moved=0, term_groups=0, tile_words=0,
    n_tiles=1, est_working_set_bytes=0, split_unpack=False,
)


def reorder_pairs(pair_ops, rows, n_inputs: int):
    """Demand-driven topological reordering of the CSE pair nodes.

    Walks the output rows in order; before a row is emitted, every
    not-yet-emitted pair node it (transitively) needs is emitted,
    dependencies first.  Pure permutation + index remap — the node
    DAG and every XOR term set are preserved exactly.

    Returns ``(pair_ops, rows, nodes_moved)`` with node indices
    remapped to the new order.
    """
    n_pairs = len(pair_ops)
    if not n_pairs:
        return tuple(pair_ops), tuple(tuple(r) for r in rows), 0
    emitted: dict[int, int] = {}  # old node idx -> new node idx
    new_pairs: list[tuple[int, int]] = []

    def emit(old: int) -> int:
        if old < n_inputs:
            return old
        hit = emitted.get(old)
        if hit is not None:
            return hit
        a, b = pair_ops[old - n_inputs]
        na, nb = emit(a), emit(b)
        new = n_inputs + len(new_pairs)
        new_pairs.append((na, nb))
        emitted[old] = new
        return new

    new_rows = tuple(
        tuple(emit(t) for t in r) for r in rows
    )
    # Any pair never reachable from a row (paar never builds one, but a
    # stored schedule could) is appended so node counts stay identical.
    for old in range(n_inputs, n_inputs + n_pairs):
        emit(old)
    moved = sum(
        1 for old, new in emitted.items()
        if pair_ops[old - n_inputs] != new_pairs[new - n_inputs]
        or old != new
    )
    return tuple(new_pairs), new_rows, moved


def group_row_terms(pair_ops, rows, n_inputs: int):
    """Group each row's terms by access pattern: CSE nodes first (newest
    first — still hot), then input planes ascending (contiguous walk).

    Returns ``(rows, term_groups)`` — term_groups counts the contiguous
    access groups across all rows (≤ 2 per row).
    """
    groups = 0
    out = []
    for r in rows:
        nodes = sorted((t for t in r if t >= n_inputs), reverse=True)
        inputs = sorted(t for t in r if t < n_inputs)
        groups += (1 if nodes else 0) + (1 if inputs else 0)
        out.append(tuple(nodes + inputs))
    return tuple(out), groups


def choose_tile(n_planes: int, nw: int, *, itemsize: int = 4):
    """Pick the chain tile width for ``n_planes`` live plane vectors of
    ``nw`` packed words each.

    Largest power-of-two ``T`` whose live set ``n_planes * T * itemsize``
    fits the budget, clamped to ``[_MIN_TILE, _MAX_TILE]``; tiling is
    only worth a scan when it yields at least two full tiles.  Returns
    ``(tile_words, n_tiles, est_working_set_bytes)`` — ``(0, 1, ws)``
    means "run the chain whole" (est is then the full-width live set).
    """
    ov = tile_override()
    if ov == 0:
        return 0, 1, n_planes * nw * itemsize
    if ov:
        t = min(ov, nw)
        if nw // t < 2:
            return 0, 1, n_planes * nw * itemsize
        return t, -(-nw // t), n_planes * t * itemsize
    budget = tile_budget_bytes()
    t = _MIN_TILE
    while (
        t * 2 <= _MAX_TILE
        and n_planes * (t * 2) * itemsize <= budget
    ):
        t *= 2
    if n_planes * t * itemsize > budget or nw // t < 2:
        # Budget unreachable even at the floor, or the operand is too
        # narrow to cut twice — whole-width is cheaper than a scan.
        return 0, 1, n_planes * nw * itemsize
    return t, -(-nw // t), n_planes * t * itemsize


def optimize_program(pair_ops, rows, n_inputs: int):
    """Reorder + group one (pair_ops, rows) straight-line XOR program.
    Returns ``(pair_ops, rows, nodes_moved, term_groups)``."""
    t0 = time.perf_counter()
    pair_ops, rows, moved = reorder_pairs(pair_ops, rows, n_inputs)
    rows, groups = group_row_terms(pair_ops, rows, n_inputs)
    # Profiler seam (obs/profiler.py): when a profiled dispatch is
    # compiling this program, its wide event attributes the optimizer's
    # own wall (compile-time work, reported in the cache block) and the
    # pass counters alongside the stage walls.  No active profile: one
    # thread-local read.
    _prof.note_opt(time.perf_counter() - t0, opt_moved=moved,
                   opt_groups=groups)
    return pair_ops, rows, moved, groups


def split_unpack(plane_words: int, *, itemsize: int = 4) -> bool:
    """Whether the unpack stage should split SWAR pieces and assembly
    into two executables (see module docstring)."""
    return plane_words * itemsize >= UNPACK_SPLIT_MIN_PLANE_BYTES


def disabled_stats() -> OptStats:
    return _DISABLED
