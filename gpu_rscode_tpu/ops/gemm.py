"""GF(2^w) GEMM — the hot loop of the whole framework, TPU-first.

Capability parity with the reference's tiled GF-GEMM kernels
(``matrix.cu:232-407``, the single hot kernel shared by encode and decode via
``encode_chunk``/``decode_chunk``, ``matrix.cu:767-905``).  The computation is
``C = A . B`` over GF(2^w): ``A`` is the tiny coefficient matrix
((n-k) x k for encode, k x k for decode), ``B`` is the (k, chunk_bytes) data
stripe, and accumulation is XOR.

TPU-native design — NOT a translation of the reference's table-lookup loops:

* **bitplane (production, MXU):** GF(2^w) multiplication by a constant is a
  GF(2)-linear map on bits, so the whole GEMM factors as ONE binary matrix
  product: ``bits(C) = expand_bitmatrix(A) @ bits(B) mod 2``.
  XOR-accumulation becomes integer accumulation + parity (sum mod 2), which
  the MXU does natively.  We pay an 8x expansion of the data into bit-planes;
  the fused Pallas kernel (:mod:`.pallas_gemm`) does that expansion in VMEM
  so HBM traffic stays 1x.  This is the strategy the bitmatrix ("Jerasure
  bit-matrix") literature uses on SIMD CPUs, re-mapped to a systolic array.

* **table (fallback, VPU):** branchless log/exp gathers XOR-folded over k
  with ``lax.scan`` — the straight analog of the reference's device tables
  (``matrix.cu:105-110``), kept because the reference's own GF(16)-vs-GF(256)
  study showed multiply-strategy choice must be measured, not assumed
  (design.tex:469-512).

* **xor (bitsliced, CPU-first):** the GF GEMM lowered to pure XOR over
  packed uint32 bit-planes with a CSE-scheduled XOR chain per output
  plane (:mod:`.xor_gemm`, docs/XOR.md) — no tables, no 8x HBM
  expansion, the SIMD-era XOR-EC formulation (arXiv 2108.02692).  Its
  schedule depends on the coefficient VALUES, so it dispatches through
  :func:`.xor_gemm.gf_matmul_xor` / the plan layer (digest-keyed), not
  through :func:`gf_matmul_jit` (which would trace ``A``).

All paths are bit-exact vs the NumPy oracle (:meth:`..ops.gf.GaloisField.matmul`).
"""

from __future__ import annotations

import functools
from typing import Literal

import jax
import jax.numpy as jnp
import numpy as np

from .gf import get_field
from .gf_jax import tables

Strategy = Literal["bitplane", "table", "pallas", "xor", "ring", "cpu"]


@functools.lru_cache(maxsize=None)
def _np_bitmats(w: int):
    return get_field(w).bitmats  # (2^w, w, w) uint8


def expand_bitmatrix_jnp(A: jnp.ndarray, w: int = 8) -> jnp.ndarray:
    """In-graph version of :meth:`GaloisField.expand_bitmatrix`:
    (p, k) GF matrix -> (p*w, k*w) 0/1 operator, via one gather from the
    per-element bitmatrix table (a (2^w, w, w) constant)."""
    bitmats = jnp.asarray(_np_bitmats(w))
    p, k = A.shape
    blocks = bitmats[A.astype(jnp.int32)]  # (p, k, w, w)
    return blocks.transpose(0, 2, 1, 3).reshape(p * w, k * w)


@functools.lru_cache(maxsize=None)
def _np_nibble_mats(w: int):
    return get_field(w).nibble_mats  # (2^w, w, 32) uint8


def expand_nibblematrix_jnp(A: jnp.ndarray, w: int = 8) -> jnp.ndarray:
    """(p, k) GF(2^8) matrix -> (p*w, k*32) one-hot-nibble operator: block
    (pi, ki) maps ``[one_hot(hi); one_hot(lo)]`` of data byte ki to the bit
    planes of ``A[pi, ki] * byte``.  Pairs with the kernel's "nibble"
    expansion (pallas_gemm)."""
    mats = jnp.asarray(_np_nibble_mats(w))
    p, k = A.shape
    blocks = mats[A.astype(jnp.int32)]  # (p, k, w, 32)
    return blocks.transpose(0, 2, 1, 3).reshape(p * w, k * 32)


def to_bitplanes(B: jnp.ndarray, w: int = 8) -> jnp.ndarray:
    """(k, m) GF elements -> (k*w, m) 0/1 planes (bit 0 = LSB first).

    Stays in the element's own width (uint8/uint16) end to end so the
    expanded intermediate is 1 byte/plane-element, not 4 — the XLA path
    materialises this array in HBM, so its dtype is the traffic."""
    k, m = B.shape
    dt = np.dtype(B.dtype) if B.dtype in (jnp.uint8, jnp.uint16) else np.dtype(np.uint16)
    shifts = jnp.arange(w, dtype=dt)
    planes = (B.astype(dt)[:, None, :] >> shifts[None, :, None]) & dt.type(1)
    return planes.reshape(k * w, m).astype(jnp.uint8)


def from_bitplanes(Cbits: jnp.ndarray, w: int = 8, dtype=jnp.uint8) -> jnp.ndarray:
    """(p*w, m) integer accumulators -> (p, m) GF elements.  Takes parity of
    each accumulator (XOR == sum mod 2) and refolds bits into elements."""
    pw, m = Cbits.shape
    shifts = jnp.arange(w, dtype=jnp.int32)
    planes = (Cbits.astype(jnp.int32) & 1).reshape(pw // w, w, m)
    return jnp.sum(planes << shifts[None, :, None], axis=1).astype(dtype)


def _dot_bits(a_bits: jnp.ndarray, b_bits: jnp.ndarray, dot_dtype) -> jnp.ndarray:
    """Binary matmul with exact integer accumulation.

    int8 x int8 -> int32 rides the MXU's integer path; bf16 -> f32 is exact
    for sums < 2^24 (contraction depth k*w <= 2^11 in any sane config).
    """
    if dot_dtype == jnp.int8:
        return jax.lax.dot(
            a_bits.astype(jnp.int8),
            b_bits.astype(jnp.int8),
            preferred_element_type=jnp.int32,
        )
    return jax.lax.dot(
        a_bits.astype(dot_dtype),
        b_bits.astype(dot_dtype),
        preferred_element_type=jnp.float32,
    ).astype(jnp.int32)


def gf_matmul_bitplane(A: jnp.ndarray, B: jnp.ndarray, w: int = 8, dot_dtype=jnp.int8) -> jnp.ndarray:
    """``C = A . B`` over GF(2^w) as one MXU matmul over GF(2) bit-planes."""
    gf = get_field(w)
    a_bits = expand_bitmatrix_jnp(A, w)
    b_bits = to_bitplanes(B, w)
    c_acc = _dot_bits(a_bits, b_bits, dot_dtype)
    return from_bitplanes(c_acc, w, dtype=gf.dtype if gf.dtype == np.uint8 else jnp.uint16)


def gf_matmul_table(A: jnp.ndarray, B: jnp.ndarray, w: int = 8) -> jnp.ndarray:
    """``C = A . B`` via branchless log/exp gathers, XOR-folded over k with a
    scan (keeps peak memory at one (p, m) slab instead of (p, k, m))."""
    log, exp = tables(w)
    gf = get_field(w)
    out_dtype = jnp.uint8 if gf.dtype == np.uint8 else jnp.uint16
    logA = log[A.astype(jnp.int32)]  # (p, k)
    logB = log[B.astype(jnp.int32)]  # (k, m)

    def step(carry, la_lb):
        la, lb = la_lb  # (p,), (m,)
        carry = carry ^ exp[la[:, None] + lb[None, :]]
        return carry, None

    init = jnp.zeros((A.shape[0], B.shape[1]), dtype=jnp.int32)
    acc, _ = jax.lax.scan(step, init, (logA.T, logB))
    return acc.astype(out_dtype)


def gf_matmul(
    A,
    B,
    w: int = 8,
    strategy: Strategy = "bitplane",
    dot_dtype=jnp.int8,
) -> jnp.ndarray:
    """Dispatch wrapper (not jitted; jit at the pipeline level)."""
    A = jnp.asarray(A)
    B = jnp.asarray(B)
    if strategy == "bitplane":
        return gf_matmul_bitplane(A, B, w, dot_dtype)
    if strategy == "table":
        return gf_matmul_table(A, B, w)
    if strategy == "pallas":
        from .pallas_gemm import gf_matmul_pallas

        return gf_matmul_pallas(A, B, w)
    if strategy == "xor":
        # Value-dependent schedule: needs a concrete A (gf_matmul_xor
        # raises an actionable TypeError on a tracer).
        from .xor_gemm import gf_matmul_xor

        return gf_matmul_xor(A, B, w)
    if strategy == "ring":
        # Value-dependent like xor: concrete A only.
        from .ring_gemm import gf_matmul_ring

        return gf_matmul_ring(A, B, w)
    raise ValueError(f"unknown strategy {strategy!r}")


@functools.partial(jax.jit, static_argnames=("w", "strategy"))
def gf_matmul_jit(A, B, w: int = 8, strategy: Strategy = "bitplane"):
    return gf_matmul(A, B, w=w, strategy=strategy)
