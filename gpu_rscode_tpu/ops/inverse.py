"""GF matrix inversion — host Gauss-Jordan (production) and a fully
on-device jitted variant.

Capability parity: the reference inverts the k x k decode submatrix on the
host CPU (``cpu-decode.c:251-298``, called from ``decode.cu:333``); a GPU
inverter exists but is dormant (``matrix.cu:667-744``) and a blocked GPU
variant was prototyped (``decode-gj.cu:1059-1201``).  The TPU build keeps the
same split — k is tiny (<= a few hundred), so the host inverts in
microseconds — but also ships :func:`invert_matrix_jax`, a single-dispatch
``lax.fori_loop`` Gauss-Jordan that runs entirely on device (what C7/C11
wanted to be: no host<->device ping-pong per pivot row).

Pivoting is done by ROW exchange, which is correct as-is for the inverse
accumulator.  The reference pivots by COLUMN exchange and has a copy-pasted
bug in all three of its implementations (the accumulator's column swap writes
to the wrong column, ``matrix.cu:449-453`` / ``cpu-decode.c:131-135`` /
``cpu-rs.c:229-233``), silently corrupting the inverse whenever a zero
diagonal pivot forces a swap.  Row pivoting avoids the permutation
book-keeping entirely; ``tests/test_matrix.py::test_invert_zero_pivot_regression``
carries the zero-pivot regression the reference would fail.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .gf import GaloisField, get_field
from .gf_jax import gf_inv, tables


class SingularMatrixError(ValueError):
    """Raised when the decode submatrix is not invertible (the reference
    aborts with "Matrix not invertible!!", cpu-rs-loop.c:83-85)."""


def invert_matrix(M: np.ndarray, gf: GaloisField | None = None) -> np.ndarray:
    """Invert a square GF matrix by Gauss-Jordan elimination with row
    pivoting.  Host-side NumPy; this is the production decode-path inverter.
    """
    gf = gf or get_field(8)
    M = np.array(M, dtype=np.int64)
    if M.ndim != 2 or M.shape[0] != M.shape[1]:
        raise ValueError(f"expected square matrix, got {M.shape}")
    k = M.shape[0]
    R = np.eye(k, dtype=np.int64)
    for i in range(k):
        nz = np.nonzero(M[i:, i])[0]
        if nz.size == 0:
            raise SingularMatrixError(f"matrix not invertible (column {i} has no pivot)")
        r = i + int(nz[0])
        if r != i:
            M[[i, r]] = M[[r, i]]
            R[[i, r]] = R[[r, i]]
        inv_p = int(gf.inv(M[i, i]))
        M[i] = gf.mul(M[i], inv_p)
        R[i] = gf.mul(R[i], inv_p)
        mask = M[:, i] != 0
        mask[i] = False
        if mask.any():
            factors = M[mask, i][:, None]
            M[mask] ^= gf.mul(factors, M[i][None, :]).astype(np.int64)
            R[mask] ^= gf.mul(factors, R[i][None, :]).astype(np.int64)
    return R.astype(gf.dtype)


def _invert_jax(M: jnp.ndarray, w: int) -> tuple[jnp.ndarray, jnp.ndarray]:
    log, exp = tables(w)
    k = M.shape[0]

    def gmul(a, b):
        return exp[log[a] + log[b]]

    A = jnp.concatenate([M.astype(jnp.int32), jnp.eye(k, dtype=jnp.int32)], axis=1)
    rows = jnp.arange(k)

    def body(i, carry):
        A, ok = carry
        col = A[:, i]
        cand = (col != 0) & (rows >= i)
        ok = ok & jnp.any(cand)
        r = jnp.argmax(cand)
        perm = rows.at[i].set(r).at[r].set(i)
        A = A[perm]
        pivot = A[i, i]
        inv_p = gf_inv(pivot, w)
        row_i = gmul(A[i], inv_p)
        A = A.at[i].set(row_i)
        elim = gmul(A[:, i][:, None], row_i[None, :])
        elim = jnp.where((rows == i)[:, None], 0, elim)
        return A ^ elim, ok

    A, ok = jax.lax.fori_loop(0, k, body, (A, jnp.bool_(True)))
    return A[:, k:], ok


def _invert_jax_nopivot(M: jnp.ndarray, w: int) -> tuple[jnp.ndarray, jnp.ndarray]:
    # Gauss-Jordan WITHOUT the row-pivot scan: the per-iteration
    # argmax + whole-matrix permutation gather in :func:`_invert_jax` is the
    # sequential bottleneck the v5e capture blamed for the k=128 device loss
    # (0.56-0.67x vs host, bench_captures/inverse_tpu_20260731T032339Z.jsonl).
    # Pivot-free elimination is exact iff every leading principal minor is
    # nonsingular — true in practice for the MDS survivor submatrices this
    # path inverts (Vandermonde/Cauchy row subsets; the reference's own
    # production inverter assumes the same and its pivot fallback is buggy,
    # cpu-decode.c:131-135).  ``ok`` goes False on any zero diagonal pivot
    # (gf_inv maps 0 -> 0 branchlessly, so the loop stays finite and the
    # garbage result is discarded); callers verify with one GF matmul and
    # fall back to the pivoting path — repair_fleet already carries exactly
    # that verify-and-fallback structure.
    log, exp = tables(w)
    k = M.shape[0]

    def gmul(a, b):
        return exp[log[a] + log[b]]

    A = jnp.concatenate([M.astype(jnp.int32), jnp.eye(k, dtype=jnp.int32)], axis=1)
    rows = jnp.arange(k)

    def body(i, carry):
        A, ok = carry
        pivot = A[i, i]
        ok = ok & (pivot != 0)
        row_i = gmul(A[i], gf_inv(pivot, w))
        A = A.at[i].set(row_i)
        elim = gmul(A[:, i][:, None], row_i[None, :])
        elim = jnp.where((rows == i)[:, None], 0, elim)
        return A ^ elim, ok

    A, ok = jax.lax.fori_loop(0, k, body, (A, jnp.bool_(True)))
    return A[:, k:], ok


_invert_jax_jit = jax.jit(_invert_jax, static_argnums=1)
_invert_nopivot_jit = jax.jit(_invert_jax_nopivot, static_argnums=1)


def invert_matrix_jax(M, w: int = 8):
    """Fully on-device Gauss-Jordan inverse.

    Returns ``(inverse int32 (k, k), ok bool)``; ``ok`` is False for singular
    input (in which case the inverse contents are garbage).  One compiled
    dispatch for the whole elimination — the design the reference's dormant
    GPU inverter was reaching for without its per-pivot host round-trips
    (``matrix.cu:678-743``).
    """
    return _invert_jax_jit(jnp.asarray(M), w)


def mds_nopivot_order(rows, k: int) -> list:
    """Reorder a k-row survivor subset so pivot-free elimination succeeds
    for the systematic layout.

    A survivor subset in chunk-index order stacks identity rows OFF their
    diagonal positions whenever a native is missing (lose chunk 0 and the
    subset starts with e_1, so M[0,0] = 0 — the elimination dies at step
    0).  Placing surviving native r (the identity row e_r) at position r
    and filling the missing-native positions with the parity rows makes
    every identity pivot 1, and the elimination only ever needs pivoting
    inside the e x e parity Schur complement (e = missing natives, tiny) —
    where a zero leading minor is rare and caught by the ``ok`` flag +
    verify-and-fallback.  Measured at k=32 (Vandermonde-mod-256 total
    matrix): 0/40 failures for realistic e <= 4 subsets; ~15 % ok=False for
    adversarial half-parity subsets (which then re-solve via the pivoting
    path).  For the Cauchy generator the Schur complement is itself a
    Cauchy submatrix, whose leading minors are Cauchy determinants — all
    nonzero — so no-pivot never fails there.  Row order of a survivor
    subset is free: the inverse just has to be paired with chunks stacked
    in the same order.
    """
    rows = list(rows)
    out: list = [None] * len(rows)
    parities = []
    for r in rows:
        if r < k:
            out[r] = r
        else:
            parities.append(r)
    free = iter(i for i, v in enumerate(out) if v is None)
    for r in parities:
        out[next(free)] = r
    return out


def invert_matrix_jax_nopivot(M, w: int = 8):
    """On-device Gauss-Jordan inverse WITHOUT row pivoting.

    Returns ``(inverse int32 (k, k), ok bool)``; ``ok`` is False when a
    diagonal pivot vanished — which for a nonsingular matrix means the
    elimination hit an unlucky leading minor and the caller must retry with
    :func:`invert_matrix_jax` (or the host inverter).  Callers are expected
    to verify the inverse (one GF matmul) regardless, the discipline
    ``api.repair_fleet`` already applies to every device inverse.
    """
    return _invert_nopivot_jit(jnp.asarray(M), w)


_invert_batch_jit = jax.jit(
    jax.vmap(_invert_jax, in_axes=(0, None)), static_argnums=1
)
_invert_batch_nopivot_jit = jax.jit(
    jax.vmap(_invert_jax_nopivot, in_axes=(0, None)), static_argnums=1
)


def invert_matrix_jax_batch(Ms, w: int = 8, *, pivot: bool = True):
    """Batched on-device inverse: (b, k, k) -> ((b, k, k) int32, (b,) ok).

    The practical realisation of the direction the reference's blocked-GPU
    inversion experiment (decode-gj.cu) pointed at: amortise inversion
    parallelism — here across the batch axis (vmap), the shape that actually
    occurs in storage systems, where each stripe of an object may have lost
    a different chunk subset and needs its own k x k inverse.  One dispatch
    inverts thousands of decode matrices.

    ``pivot=False`` runs the scan-free elimination (:func:`_invert_jax_nopivot`)
    — no per-step argmax/permutation, the sequential cost that made the
    pivoting version LOSE to the host loop at k=128 on v5e
    (inverse_tpu_20260731T032339Z.jsonl).  ``ok`` additionally goes False on
    any zero diagonal pivot; since MDS survivor submatrices essentially
    never produce one, the intended production pattern is
    no-pivot first, verify each inverse, re-solve the rare failures with
    the pivoting/host path.
    """
    jit = _invert_batch_jit if pivot else _invert_batch_nopivot_jit
    return jit(jnp.asarray(Ms), w)
