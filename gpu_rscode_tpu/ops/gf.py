"""GF(2^w) arithmetic core — tables, scalar ops, and bit-plane linear maps.

This is the L1 layer of the TPU-native Reed-Solomon framework (capability
parity with the reference's device GF layer, ``matrix.cu:24-220``, its host
twin ``cpu-decode.c:24-100``, the legacy multi-width library
``galoisfield.cu`` (w in {4, 8, 16}), and the branchless table scheme the
reference's R&D series converged on, ``cpu-rs-log-exp-3.c:51-98``).

Design notes (TPU-first, NOT a translation):

* The canonical table layout is the fully-branchless one: ``log[0]`` holds a
  large sentinel (``2*(order)``, where ``order = 2^w - 1``) and the exp table
  is extended and zero-padded so that ``exp[log[a] + log[b]]`` is correct for
  ALL byte pairs including zeros — no zero-operand branch anywhere.  The
  reference arrived at exactly this scheme for its GPU constant tables
  (1021-entry exp, ``gflog[0] = 510`` for w=8).

* The *production* multiply path on TPU does not use these tables at all:
  GF(2^w) multiplication by a constant ``a`` is a GF(2)-linear map on the bit
  vector of ``b``, so a whole RS encode is one (w*p, w*k) x (w*k, m) binary
  matrix product — XOR-accumulation becomes integer matmul + parity, which is
  native MXU work.  :func:`bitmatrix` / :func:`expand_bitmatrix` build those
  operators; ``ops/gemm.py`` consumes them.

* Everything here is NumPy (host-side): tables are built once per field width
  and are tiny.  The JAX/Pallas kernels import the *constants* produced here.
"""

from __future__ import annotations

import functools

import numpy as np

# Primitive polynomials, one per supported field width (same fields the
# reference's legacy library supported, galoisfield.cu:22-25).
# w=8 is 0x11D = octal 0435 = x^8+x^4+x^3+x^2+1, the poly baked into the
# reference's in-kernel table generator (matrix.cu:47-75).
PRIMITIVE_POLY = {
    4: 0x13,  # x^4 + x + 1
    8: 0x11D,  # x^8 + x^4 + x^3 + x^2 + 1
    16: 0x1100B,  # x^16 + x^12 + x^3 + x + 1
}


def _carryless_mul_mod(a: int, b: int, w: int, poly: int) -> int:
    """Bitwise shift-add GF multiply (the no-table oracle; the reference's
    ``cpu-rs-loop.c:51-64`` used the same strategy as its table-free variant).
    Used only to validate the tables in tests."""
    r = 0
    while b:
        if b & 1:
            r ^= a
        b >>= 1
        a <<= 1
        if a >> w:
            a ^= poly
    return r


class GaloisField:
    """Tables and vectorised host-side ops for GF(2^w), w in {4, 8, 16}.

    Attributes (all NumPy arrays, suitable for shipping to device constants):

    ``log``
        ``(2^w,) int32``, ``log[0] = 2*order`` sentinel (branchless scheme).
    ``exp``
        ``(4*order + 1,)`` of the element dtype: ``exp[i] = g^(i mod order)``
        for ``i < 2*order``, zero for ``i >= 2*order``.  Any index touching a
        zero operand's sentinel lands in the zero pad, so
        ``exp[log[a] + log[b]]`` needs no branch.  (w=8: 1021 entries,
        matching the reference's ``gfexp_cMem[1021]`` / ``gflog[0]=510``.)
    ``mul_table``
        Full multiplication table ``(2^w, 2^w)`` — only materialised for
        w <= 8 (the w=8 64 KB table mirrors the reference's
        ``cpu-rs-full.c`` strategy; for w=16 it would be 8 GB).
    """

    def __init__(self, w: int = 8):
        if w not in PRIMITIVE_POLY:
            raise ValueError(f"unsupported field width {w}; choose from {sorted(PRIMITIVE_POLY)}")
        self.w = w
        self.poly = PRIMITIVE_POLY[w]
        self.size = 1 << w  # field cardinality 2^w
        self.order = self.size - 1  # multiplicative group order
        self.dtype = np.uint8 if w <= 8 else np.uint16

        sentinel = 2 * self.order
        log = np.zeros(self.size, dtype=np.int32)
        exp_core = np.zeros(self.order, dtype=np.int64)
        x = 1
        for i in range(self.order):
            exp_core[i] = x
            log[x] = i
            x <<= 1
            if x & self.size:
                x ^= self.poly
        log[0] = sentinel

        # exp indices seen in practice: mul -> log[a]+log[b] in [0, 2*sentinel];
        # div -> log[a] + order - log[b] in [0, sentinel + order].  Pad to
        # 2*sentinel + 1 and zero everything >= sentinel so sentinel-tainted
        # indices read 0.
        exp = np.zeros(2 * sentinel + 1, dtype=self.dtype)
        idx = np.arange(sentinel) % self.order
        exp[:sentinel] = exp_core[idx].astype(self.dtype)
        self.log = log
        self.exp = exp
        self.sentinel = sentinel

        if w <= 8:
            a = np.arange(self.size, dtype=np.int64)
            self.mul_table = self.exp[self.log[a][:, None] + self.log[a][None, :]]
        else:
            self.mul_table = None

        # Per-bit multiply operators: bitmat_by_value[v] is the (w, w) GF(2)
        # matrix M_v with bits(v * b) = M_v @ bits(b) mod 2.  Column j of M_v
        # is the bit vector of v * (1 << j).  Built lazily for w=16.
        self._bitmats: np.ndarray | None = None
        self._nibble_mats: np.ndarray | None = None

    # ----- scalar / vectorised field ops -------------------------------------

    def mul(self, a, b):
        """Elementwise GF multiply of arrays/scalars (branchless log/exp)."""
        a = np.asarray(a, dtype=np.int64)
        b = np.asarray(b, dtype=np.int64)
        return self.exp[self.log[a] + self.log[b]]

    def div(self, a, b):
        """Elementwise GF divide; division by zero raises."""
        a = np.asarray(a, dtype=np.int64)
        b = np.asarray(b, dtype=np.int64)
        if np.any(b == 0):
            raise ZeroDivisionError("GF division by zero")
        return self.exp[self.log[a] + self.order - self.log[b]]

    def pow(self, a, e):
        """GF power a**e (e a non-negative integer array/scalar).

        Matches the reference's Vandermonde generator contract
        (``matrix.cu:204-208``): 0**0 == 1, 0**e == 0 for e > 0.
        """
        a = np.asarray(a, dtype=np.int64)
        e = np.asarray(e, dtype=np.int64)
        la = self.log[a]
        # exp index for nonzero a; zero a handled by sentinel only when e > 0.
        idx = (la * e) % self.order
        out = self.exp[idx]
        zero_base = (a == 0) & (e > 0)
        out = np.where(zero_base, 0, out)
        return out.astype(self.dtype) if out.ndim else self.dtype(out)

    def inv(self, a):
        """Multiplicative inverse; inverse of zero raises."""
        a = np.asarray(a, dtype=np.int64)
        if np.any(a == 0):
            raise ZeroDivisionError("GF inverse of zero")
        return self.exp[self.order - self.log[a]]

    def matmul(self, A, B):
        """GF matrix product (XOR-accumulated).  Host oracle for the TPU GEMM
        (role of the reference's naive CPU ``matrix_mul``, cpu-rs.c:182-198).
        """
        A = np.asarray(A, dtype=np.int64)
        B = np.asarray(B, dtype=np.int64)
        if A.ndim != 2 or B.ndim != 2 or A.shape[1] != B.shape[0]:
            raise ValueError(f"shape mismatch {A.shape} @ {B.shape}")
        out = np.zeros((A.shape[0], B.shape[1]), dtype=self.dtype)
        for t in range(A.shape[1]):
            out ^= self.mul(A[:, t][:, None], B[t][None, :])
        return out

    # ----- GF(2) bit-plane view (the TPU-native representation) --------------

    def bitmatrix(self, v: int) -> np.ndarray:
        """(w, w) uint8 GF(2) matrix of multiply-by-v: bits(v*b) = M @ bits(b).

        ``M[i, j] = bit i of (v * 2^j)``; bit 0 is the LSB.
        """
        cols = self.mul(int(v), 1 << np.arange(self.w, dtype=np.int64))
        shifts = np.arange(self.w, dtype=np.int64)
        return ((cols[None, :].astype(np.int64) >> shifts[:, None]) & 1).astype(np.uint8)

    @property
    def bitmats(self) -> np.ndarray:
        """(2^w, w, w) uint8 — bitmatrix(v) for every field element."""
        if self._bitmats is None:
            v = np.arange(self.size, dtype=np.int64)
            prods = self.mul(v[:, None], 1 << np.arange(self.w, dtype=np.int64)[None, :])
            shifts = np.arange(self.w, dtype=np.int64)
            self._bitmats = (
                (prods[:, None, :].astype(np.int64) >> shifts[None, :, None]) & 1
            ).astype(np.uint8)
        return self._bitmats

    @property
    def nibble_mats(self) -> np.ndarray:
        """(2^w, w, 32) uint8 — one-hot-nibble multiply operator blocks.

        ``nibble_mats[c, s, v] = bit s of c * val(v)`` with ``val(v) = v<<4``
        for v < 16 (high nibble) and ``val(v) = v - 16`` for v >= 16 (low).
        Since ``b = (hi<<4) ^ lo``, stacking ``one_hot(hi)`` over
        ``one_hot(lo)`` gives ``bits(c*b) = nibble_mats[c] @ stack mod 2`` —
        the MXU-side analog of the reference's GF(16) nibble-table strategy
        (gf16.h, design.tex:190-209, and the 4 KB half-byte tables of
        cpu-rs-double.c:52-55).  w=8 only.
        """
        if self.w != 8:
            raise ValueError("nibble operator is defined for w=8 only")
        if self._nibble_mats is None:
            vals = np.concatenate(
                [np.arange(16, dtype=np.int64) << 4, np.arange(16, dtype=np.int64)]
            )
            prods = self.mul(np.arange(256, dtype=np.int64)[:, None], vals[None, :])
            shifts = np.arange(8, dtype=np.int64)
            self._nibble_mats = (
                (prods[:, None, :].astype(np.int64) >> shifts[None, :, None]) & 1
            ).astype(np.uint8)
        return self._nibble_mats

    def expand_bitmatrix(self, A: np.ndarray) -> np.ndarray:
        """Expand a (p, k) GF coefficient matrix to its (p*w, k*w) GF(2)
        operator.  Block (pi, ki) is ``bitmatrix(A[pi, ki])``.

        This is what turns an RS encode/decode into ONE binary matmul:
        ``bits(C) = expand_bitmatrix(A) @ bits(B) mod 2``.
        """
        A = np.asarray(A)
        p, k = A.shape
        blocks = self.bitmats[A.astype(np.int64)]  # (p, k, w, w)
        return blocks.transpose(0, 2, 1, 3).reshape(p * self.w, k * self.w)


@functools.lru_cache(maxsize=None)
def get_field(w: int = 8) -> GaloisField:
    """Singleton per-width field instance."""
    return GaloisField(w)


# The default field everything operates in (the reference's master branch is
# GF(256); its `extend` branch and legacy library cover w=4/16 — supported
# here via get_field(4) / get_field(16)).
GF8 = get_field(8)
