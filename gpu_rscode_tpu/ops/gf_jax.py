"""JAX-side GF(2^w) primitives: table constants and elementwise ops.

These are the on-device counterparts of :mod:`.gf` (role of the reference's
``__device__ __const__`` table copies, ``matrix.cu:34-39``).  On TPU the
tables live in whatever memory XLA chooses (they are tiny; XLA keeps them
resident), and the elementwise ops lower to vector gathers on the VPU.

The table-gather path is the *fallback* multiply strategy; the production
GEMM uses the bit-plane MXU formulation in :mod:`.gemm`.  Both are kept —
the reference's own GF(16)-vs-GF(256) experiment showed the strategy choice
is worth benchmarking, not assuming (design.tex:469-512).
"""

from __future__ import annotations

import functools

import jax.numpy as jnp
import numpy as np

from .gf import get_field


@functools.lru_cache(maxsize=None)
def _np_tables(w: int = 8):
    gf = get_field(w)
    return np.asarray(gf.log, dtype=np.int32), gf.exp.astype(np.int32)


def tables(w: int = 8):
    """(log, exp) as int32 device constants for field width ``w``.

    The cache holds NumPy; conversion happens per call so tables embed as
    XLA constants whether called inside or outside a trace (caching device
    arrays created mid-trace would leak tracers).
    """
    log, exp = _np_tables(w)
    return jnp.asarray(log), jnp.asarray(exp)


def mul_table(w: int = 8):
    """Full (2^w, 2^w) multiply table (w <= 8 only) as a device constant —
    the one-gather strategy (reference's ``cpu-rs-full.c`` 64K-table study)."""
    gf = get_field(w)
    if gf.mul_table is None:
        raise ValueError(f"full mul table not materialised for w={w}")
    return jnp.asarray(gf.mul_table)


def gf_mul(a, b, w: int = 8):
    """Elementwise GF multiply of int arrays (branchless log/exp gathers)."""
    log, exp = tables(w)
    return exp[log[a] + log[b]]


def gf_inv(a, w: int = 8):
    """Elementwise multiplicative inverse.  Branchless: zero deterministically
    maps to 0 (its sentinel log lands the index in the zero pad) — callers
    that need division-by-zero to be an *error* must check beforehand."""
    gf = get_field(w)
    log, exp = tables(w)
    return exp[(gf.order - log[a]) % (2 * gf.sentinel + 1)]
