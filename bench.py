"""Benchmark — encode GB/s at the BASELINE headline config (k=10, n=14).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "detail"}.
Baseline: the reference's published GPU encode bandwidth, 1356.835 MB/s
(Tesla C2050, design.tex:490; BASELINE.md) == 1.356835 GB/s.

Method: a (k=10, p=4) stripe resident on the device is encoded by each
available GEMM strategy (fused Pallas kernel first, then the XLA bit-plane
path segmented to bound HBM, then the table path); every strategy's output
is verified bit-exact against the native CPU oracle on a sample before its
time counts.  The reported number is the best verified strategy's
steady-state device throughput (file bytes / wall), comparable to the
reference's kernel-bandwidth figure (which likewise excludes PCIe copies).
"""

import json
import sys
import threading
import time as _time_mod

import numpy as np

_T0 = _time_mod.time()


def _mark(phase: str) -> None:
    """Phase timestamp on stderr — the bench runs under a driver timeout, so
    when it is slow or killed the log must show where the time went."""
    print(f"# [{_time_mod.time() - _T0:7.1f}s] {phase}", file=sys.stderr, flush=True)


# One-line contract, enforced: success, failure, retry-loop forward and
# the wedge watchdog all race to this gate; the first wins, the rest no-op.
_EMIT_LOCK = threading.Lock()
_EMITTED = False


def _emit_line(line: str) -> bool:
    global _EMITTED
    with _EMIT_LOCK:
        if _EMITTED:
            return False
        _EMITTED = True
    print(line, flush=True)
    return True


def _emit(backend: str, value: float, detail: dict) -> bool:
    """The bench's single machine-readable output line — one schema, used by
    the success, strategy-failure, crash and watchdog paths alike.  Returns
    whether THIS call won the one-line gate."""
    return _emit_line(
        json.dumps(
            {
                "metric": f"encode_bandwidth_k{K}_n{K + P}_{backend}",
                "value": round(value, 3),
                "unit": "GB/s",
                "vs_baseline": round(value / BASELINE_GBPS, 2),
                "detail": detail,
            }
        )
    )


def _committed_tpu_captures() -> list:
    import glob
    import os

    return sorted(
        glob.glob(
            os.path.join(os.path.dirname(__file__) or ".",
                         "bench_captures", "bench_tpu_*.json")
        )
    )


def _committed_tpu_headline(caps: list | None = None) -> dict | None:
    """Headline numbers from the newest VALID committed hardware capture,
    inlined into a CPU-fallback artifact: a reader of BENCH_r{N}.json
    should see the hardware evidence (value + strategy + decode +
    recovery), not just file paths to go look up.  Scans newest-to-oldest
    and skips zero-value failure lines — capture promotion only checks for
    a TPU metric name, so an all-strategies-failed hardware run can sit
    newest in the list and must not mask the real evidence behind it."""
    import os

    if caps is None:
        caps = _committed_tpu_captures()
    for path in reversed(caps):
        try:
            with open(path) as fp:
                d = json.loads(fp.read().strip().splitlines()[-1])
            if not (isinstance(d.get("value"), (int, float)) and d["value"] > 0):
                continue
            if not str(d.get("metric", "")).endswith("_tpu"):
                # A mislabeled non-hardware file under the bench_tpu_
                # prefix must not become the inlined hardware evidence.
                continue
            det = d.get("detail") or {}
            return {
                "file": os.path.basename(path),
                "metric": d.get("metric"),
                "value": d.get("value"),
                "unit": d.get("unit"),
                "vs_baseline": d.get("vs_baseline"),
                "strategy": det.get("strategy"),
                "decode_gbps": det.get("decode_gbps"),
                "recovery_latency_ms": det.get("recovery_latency_ms"),
            }
        except Exception:  # a malformed capture must not break the line
            continue
    return None


def _attach_committed_evidence(detail: dict) -> dict:
    """Attach the committed hardware evidence (capture path list + newest
    VALID headline inlined) to a CPU/error artifact's detail dict.  ONE
    copy shared by all three emission paths — main's fallback, the
    watchdog's held-CPU line, and the watchdog's error line — so the
    artifacts cannot drift.  Exception-safe by contract: two of those
    callers run on the watchdog thread, where a raised exception would
    kill the thread silently and lose the output line entirely."""
    try:
        caps = _committed_tpu_captures()
        if caps:
            detail["committed_tpu_captures"] = caps
        headline = _committed_tpu_headline(caps)
        if headline:
            detail["latest_committed_tpu"] = headline
    except Exception:
        pass  # evidence is best-effort; the line itself must still emit
    return detail


_PARTIAL = None  # (backend, best, detail) once a VERIFIED number exists


def _arm_wedge_watchdog(delay: float | None = None) -> None:
    """Emit the JSON line even if the device WEDGES mid-measurement.

    The probe protects against a tunnel that is down at start; this guards
    the TOCTOU hole after it: a healthy probe followed by a mid-run hang
    blocks the main thread inside a device wait, where neither exception
    handlers nor signal handlers can run — observed 2026-07-30 as an rc=124
    bench with NO output line.  A daemon timer fires from its own thread
    before any plausible driver timeout and hard-exits after emitting:

    * the held result (exit 0) when a verified encode number is already in
      hand (``_PARTIAL``, a snapshot re-published as each strategy/decode
      result lands) — a wedge during a later strategy, decode timing or a
      long retry phase must not discard the round's headline measurement;
    * otherwise the error line with pointers to the committed hardware
      captures (exit 1).

    Armed unconditionally: in the hardware child the parent's subprocess
    timeout expires long before this fires, and a direct hardware-only run
    (RS_BENCH_NO_FALLBACK) is the MOST exposed to a wedge, not the least.

    Re-arming (``delay`` seconds from NOW) replaces the pending timer: the
    retry loop extends the deadline before launching a hardware child so a
    watchdog armed for the base budget cannot latch the held CPU line
    while the child is about to deliver the TPU line (ADVICE r3).
    """
    import os

    budget = (
        delay if delay is not None
        else float(os.environ.get("RS_BENCH_WATCHDOG_S", "480"))
    )

    def fire() -> None:
        held = _PARTIAL  # read once; main keeps re-binding fresh snapshots
        if held is not None:
            backend, best, detail = held
            try:
                extra = {}
                if backend != "tpu":
                    # The held CPU line gets the same hardware evidence
                    # the normal fallback path adds at the end of main()
                    # — a wedge must not strip it.
                    _attach_committed_evidence(extra)
                emitted = _emit(
                    backend, best[1],
                    {
                        "strategy": best[0], **detail, **extra,
                        "watchdog": "fired before the run fully completed; "
                                    "value is the verified encode "
                                    "measurement",
                    },
                )
            except Exception:
                # Never die silently in the watchdog thread.  If the first
                # _emit latched the gate and THEN failed mid-print (broken
                # stdout), the fallback can't print either — exit anyway:
                # a lingering wedged process with no line is the one
                # outcome this thread exists to prevent.
                try:
                    _emit(
                        backend, best[1],
                        {"strategy": best[0], "watchdog": "fired"},
                    )
                finally:
                    os._exit(0)
            if emitted:
                _mark("watchdog fired; emitted the held result")
                os._exit(0)
        elif _emit(
            "error", 0.0,
            _attach_committed_evidence({
                "error": f"watchdog: no result after {budget:.0f}s "
                         "(device wedged mid-run?)",
            }),
        ):
            _mark("watchdog fired; device wedged mid-run")
            os._exit(1)

    global _WATCHDOG
    if _WATCHDOG is not None:
        _WATCHDOG.cancel()
    _WATCHDOG = threading.Timer(budget, fire)
    _WATCHDOG.daemon = True
    _WATCHDOG.start()


_WATCHDOG = None

from gpu_rscode_tpu.tools._bench_timing import time_device_fn as _time

K, P = 10, 4
BASELINE_GBPS = 1.356835


_PROBE_HUNG = object()  # sentinel: the probe subprocess had to be killed


def _probe_subprocess(code: str, env: dict, timeout: float):
    """Run a tiny probe script in a throwaway subprocess.  A busy axon
    tunnel makes jax client-create BLOCK rather than raise (the
    MULTICHIP_r01 rc=124 mode), and an in-process hang could never be
    recovered — hence the subprocess.  Returns the last stdout line,
    ``_PROBE_HUNG`` on timeout, or ``None`` with the stderr tail printed
    on nonzero exit.

    The child is stopped with SIGTERM (grace, then SIGKILL only as a last
    resort) — a blocked client is *waiting* for the tunnel lease, not
    holding it, so terminating it does not wedge the lease.
    """
    import subprocess

    p = subprocess.Popen(
        [sys.executable, "-c", code],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    try:
        out, err = p.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        p.terminate()
        try:
            p.wait(timeout=15)
        except subprocess.TimeoutExpired:
            p.kill()
        print(f"# backend probe hung >{timeout}s (tunnel busy?)",
              file=sys.stderr)
        return _PROBE_HUNG
    if p.returncode != 0:
        print(f"# backend probe failed: {err.strip()[-200:]}",
              file=sys.stderr)
        return None
    return out.strip().splitlines()[-1] if out.strip() else None


def _probe_backend(env_platform=None, timeout=120):
    """Probe which jax backend would initialise.  Returns
    (backend_name|None, hung)."""
    import os

    env = dict(os.environ)
    if env_platform is not None:
        env["JAX_PLATFORMS"] = env_platform
    got = _probe_subprocess(
        "import jax; print(jax.default_backend())", env, timeout
    )
    if got is _PROBE_HUNG:
        return None, True
    return got, False


def _init_backend():
    """Initialise a jax backend, surviving a flaky OR wedged TPU tunnel.

    Round-1 postmortem (BENCH_r01 rc=1): one transient axon client-create
    failure killed the whole bench before its first measurement; the other
    tunnel failure mode blocks forever.  Each candidate backend is first
    probed in a subprocess with a timeout; only a probe that comes back
    healthy is initialised in-process.  Falls back to forced cpu with the
    axon factory deregistered (TPU retries continue at emit time, see
    _tpu_retry_until_deadline).  Returns (jax, backend_name); the bench
    ALWAYS emits its JSON line with whatever backend this lands on.
    """
    import os
    import time

    def _no_fallback_guard(name: str) -> None:
        # The hardware child must never measure on CPU under ANY of the
        # probe paths, not just the forced-cpu last resort — a tunnel that
        # flaps back down between the parent's probe and the child's start
        # would otherwise make the child burn its whole timeout re-running
        # the CPU bench (and recursing into its own retry loop).
        if os.environ.get("RS_BENCH_NO_FALLBACK") and name == "cpu":
            raise SystemExit("probe landed on cpu and RS_BENCH_NO_FALLBACK set")

    hung = False
    for attempt in range(2):
        # 75 s per probe, 2 attempts: a healthy tunnel answers in ~10-30 s;
        # anything slower is the wedge mode, and every second burned here
        # comes out of the retry loop's window (the r03 postmortem: a
        # single 120 s probe + one-shot second chance consumed the budget
        # that staggered retries should have had).
        name, hung = _probe_backend(timeout=75)
        if name:
            _no_fallback_guard(name)
            import jax

            # Residual TOCTOU: the tunnel could wedge between the probe and
            # this init; in-process protection is impossible (a blocked
            # client-create ignores signals), the probe narrows the window
            # to seconds and the driver runs the bench single-tenant.
            jax.devices()
            return jax, jax.default_backend()
        if hung:
            # A wedged tunnel does not un-wedge in seconds — fall through
            # to the defused cpu path NOW; the retry loop keeps probing for
            # the rest of the budget after the CPU line is in hand.
            break
        if attempt < 1:
            time.sleep(5.0)
    if not hung:
        # Auto-pick ('' = let jax choose any available platform).
        name, hung = _probe_backend(env_platform="", timeout=45)
        if name:
            _no_fallback_guard(name)
            import jax

            os.environ["JAX_PLATFORMS"] = ""
            jax.config.update("jax_platforms", "")
            jax.devices()
            return jax, jax.default_backend()
    if os.environ.get("RS_BENCH_NO_FALLBACK"):
        # The hardware child must never report a CPU number (its parent
        # already holds one) — fail fast instead.
        raise SystemExit("no TPU backend and RS_BENCH_NO_FALLBACK set")
    # Last resort: forced cpu, axon factory removed so nothing can dial the
    # tunnel again (shared landmine-defusal helper, see _axon_guard.py).
    from _axon_guard import defuse_axon

    jax = defuse_axon(allow_initialised=True)
    jax.devices()  # if even cpu fails there is nothing to salvage
    print("# TPU backend unavailable; benching on cpu", file=sys.stderr)
    return jax, jax.default_backend()


def _probe_tpu_once(timeout: float = 60.0) -> str:
    """Subprocess probe for the device platform ('' on failure/hang).  The
    fallback path pinned JAX_PLATFORMS=cpu in os.environ — the probe child
    must not inherit that or it can only ever answer "cpu"."""
    import os

    probe_env = dict(os.environ)
    probe_env.pop("JAX_PLATFORMS", None)
    got = _probe_subprocess(
        "import jax; print(jax.devices()[0].platform.lower())",
        probe_env, timeout,
    )
    return got if isinstance(got, str) else ""


# A hardware child needs this much wall at minimum (backend init ~30 s +
# first kernel compiles ~40 s + timed strategies + decode); probing later
# than budget - (this + margin) cannot produce a TPU line anymore.
_MIN_CHILD_S = 150.0


def _tpu_retry_until_deadline() -> bool:
    """Keep probing for the tunnel across the WHOLE remaining budget.

    Round-3 postmortem: the one-shot "second chance" probed exactly once,
    ~60 s after the CPU result, against a tunnel that flaps on multi-minute
    timescales — and the round shipped a 0.33x CPU line while committed
    captures showed 47.7x on the same config.  With the CPU result safely
    held (``_PARTIAL`` + watchdog), this loop probes every ~15 s until the
    watchdog budget minus a minimum-viable child window is exhausted; on
    the first healthy probe it re-runs the bench in a hardware-only child
    (fresh interpreter — this one's jax is pinned to the defused cpu
    backend; RS_BENCH_NO_FALLBACK so it can never recurse into a second
    CPU measurement) and forwards the child's TPU JSON line as OUR single
    output line.  Returns True iff that happened.

    The watchdog is RE-ARMED to cover each child launch (ADVICE r3): a
    timer armed for the base budget must not fire mid-child, latch the
    held CPU line and discard the TPU line the child was about to produce.
    On loop exhaustion the caller emits the held CPU line directly — the
    watchdog stays as the wedge backstop, not the normal exit path.
    """
    import os
    import subprocess

    budget = float(os.environ.get("RS_BENCH_WATCHDOG_S", "480"))
    attempt = 0
    while True:
        elapsed = _time_mod.time() - _T0
        remaining = budget - elapsed
        # Reserve only a FAST probe (~30 s, the healthy-tunnel answer time),
        # not the 60 s hung-probe worst case: a hung probe near the deadline
        # means no child launches anyway (the viability check below), while
        # a healthy late probe is exactly the flap this loop exists to catch.
        if remaining < _MIN_CHILD_S + 40:
            _mark(
                f"retry window exhausted after {attempt} probe(s) "
                f"({remaining:.0f}s left < child minimum); keeping cpu line"
            )
            return False
        attempt += 1
        platform = _probe_tpu_once(timeout=60)
        if platform != "tpu":
            _mark(f"probe {attempt}: saw {platform or 'nothing'}; retrying")
            _time_mod.sleep(15.0)
            continue
        child_timeout = min(300.0, budget - (_time_mod.time() - _T0) - 15)
        if child_timeout < _MIN_CHILD_S:
            _mark(
                f"tunnel healthy but only {child_timeout:.0f}s left — below "
                f"the {_MIN_CHILD_S:.0f}s child minimum; keeping cpu line"
            )
            return False
        # Extend the wedge deadline past the child's own timeout: the
        # child is time-bounded by subprocess.run, so the parent cannot
        # wedge here, and the held CPU line is emitted on every exit path.
        _arm_wedge_watchdog(child_timeout + 60)
        _mark(
            f"probe {attempt}: tunnel healthy; hardware child "
            f"(timeout {child_timeout:.0f}s)"
        )
        env = dict(os.environ)
        env["RS_BENCH_NO_FALLBACK"] = "1"
        env.pop("JAX_PLATFORMS", None)
        try:
            run = subprocess.run(
                [sys.executable, __file__],
                env=env, capture_output=True, text=True,
                timeout=child_timeout,
            )
        except subprocess.TimeoutExpired:
            _mark("hardware child timed out; keeping cpu line")
            return False
        if run.returncode == 0:
            for line in run.stdout.splitlines():
                if line.startswith("{") and "_tpu" in line.split(",")[0]:
                    try:
                        if json.loads(line).get("value", 0) > 0:
                            return _emit_line(line)
                    except ValueError:
                        pass
        tail = run.stderr.strip().splitlines()[-1:] if run.stderr else []
        _mark(
            f"hardware child rc={run.returncode} had no good TPU line "
            f"({tail}); keep probing"
        )
        # A fast child failure (tunnel flapped back down before its init)
        # leaves window — loop; a slow one exhausts it on the next check.
        # Restore the wedge deadline to the REMAINING base budget: the
        # child-extended timer would otherwise fire mid-loop under a large
        # budget and os._exit with the held CPU line, truncating the very
        # retry window this loop exists to provide.  And back off like the
        # probe-failure branch — a persistently fast-failing child must
        # not burn the budget in back-to-back launches.
        _arm_wedge_watchdog(max(30.0, budget - (_time_mod.time() - _T0)))
        _time_mod.sleep(15.0)


def _verify(small_fn, oracle_slice):
    """Bit-exactness gate on a small slab (cheap: runs the strategy on the
    4 KB slice only, not the full stripe)."""
    got = np.asarray(small_fn())
    if not np.array_equal(got, oracle_slice):
        raise AssertionError("output mismatch vs CPU oracle")


def main() -> None:
    _arm_wedge_watchdog()
    _mark("backend init")
    jax, backend = _init_backend()
    _mark(f"backend ready: {backend}")

    from gpu_rscode_tpu import native
    from gpu_rscode_tpu.models.vandermonde import vandermonde_matrix
    from gpu_rscode_tpu.ops.gemm import gf_matmul_jit
    from gpu_rscode_tpu.ops.pallas_gemm import gf_matmul_pallas

    # The tunnel backend may self-report as "axon" while its devices are real
    # TPU chips — size and label the run by the device platform, not the
    # backend registration name.
    platform = jax.devices()[0].platform.lower()
    on_tpu = backend == "tpu" or platform == "tpu" or backend == "axon"
    backend = "tpu" if on_tpu else backend
    m = (32 * 1024 * 1024) if on_tpu else (2 * 1024 * 1024)  # bytes per chunk
    seg = 4 * 1024 * 1024  # XLA bitplane segment (bounds HBM expansion)

    A = vandermonde_matrix(P, K)
    rng = np.random.default_rng(0)
    B_host = rng.integers(0, 256, size=(K, m), dtype=np.uint8)
    Ad = jax.device_put(A)
    Bd = jax.device_put(B_host)
    sample = native.gemm(A, B_host[:, :4096])  # CPU-oracle verification slab
    Bd_small = jax.device_put(B_host[:, :4096])

    def run_pallas():
        return gf_matmul_pallas(Ad, Bd)

    def run_bitplane():
        outs = [
            gf_matmul_jit(Ad, Bd[:, off : off + seg], strategy="bitplane")
            for off in range(0, m, seg)
        ]
        return jax.numpy.concatenate(outs, axis=1) if len(outs) > 1 else outs[0]

    def run_table():
        outs = [
            gf_matmul_jit(Ad, Bd[:, off : off + seg], strategy="table")
            for off in range(0, m, seg)
        ]
        return jax.numpy.concatenate(outs, axis=1) if len(outs) > 1 else outs[0]

    from gpu_rscode_tpu.ops.xor_gemm import gf_matmul_xor

    def run_xor():
        # The XOR-lowered bitsliced strategy (docs/XOR.md): same segment
        # discipline as the other XLA paths (the packed planes expand in
        # memory 1x, but the staged pipeline still prefers bounded
        # dispatch extents).
        outs = [
            gf_matmul_xor(A, Bd[:, off : off + seg], 8)
            for off in range(0, m, seg)
        ]
        return jax.numpy.concatenate(outs, axis=1) if len(outs) > 1 else outs[0]

    small = {
        "pallas": lambda: gf_matmul_pallas(Ad, Bd_small),
        "bitplane": lambda: gf_matmul_jit(Ad, Bd_small, strategy="bitplane"),
        "table": lambda: gf_matmul_jit(Ad, Bd_small, strategy="table"),
        "xor": lambda: gf_matmul_xor(A, Bd_small, 8),
    }
    candidates = [
        ("pallas", run_pallas),
        ("xor", run_xor),
        ("bitplane", run_bitplane),
        ("table", run_table),
    ]
    import os

    # Hardware CHILD of the retry loop: it runs under a hard subprocess
    # timeout against a tunnel that just recovered — every strategy costs
    # ~30-45 s of remote compiles, and the headline needs only the first
    # strategy that verifies and times (fastest-first order, so that is
    # the fused kernel unless it fails; the slower strategies' numbers
    # exist in committed captures).  The loop breaks after that first
    # success instead of spending the child's budget on the rest.
    fast_child = bool(on_tpu and os.environ.get("RS_BENCH_NO_FALLBACK"))
    if not on_tpu and native.available():
        # The threaded C++ host codec (strategy="cpu") is the strongest
        # non-device path (~2.3x the XLA table strategy on this host) — a
        # tunnel-outage fallback line should reflect the framework's best
        # CPU capability, not just its device strategies.  Verified against
        # the independent pure-NumPy bitwise oracle (native.gemm itself is
        # the usual oracle, so it cannot self-verify — and gated on the
        # real C++ library being loaded, since native.gemm's NumPy fallback
        # IS that oracle).
        from gpu_rscode_tpu.ops.gf import get_field

        numpy_oracle = get_field(8).matmul(A, B_host[:, :4096])

        def run_native():
            return native.gemm(A, B_host)

        small["native"] = lambda: native.gemm(A, B_host[:, :4096])
        candidates.append(("native", run_native))
        sample_by_name = {"native": numpy_oracle}
    else:
        sample_by_name = {}
    data_bytes = K * m
    # Parallelism identity (the ROADMAP's multi-core XLA scaling claim
    # needs the cores each number was measured on — see
    # obs/runlog.capture_header, which records the same fields for every
    # tools/* capture): physical CPUs and the affinity-limited intra-op
    # thread count XLA CPU can actually use.
    from gpu_rscode_tpu.obs import runlog as _runlog_mod

    detail = {
        "host_cpus": os.cpu_count() or 1,
        "intra_op_threads": _runlog_mod.intra_op_threads(),
    }
    best = (None, 0.0)
    global _PARTIAL
    for name, fn in candidates:
        try:
            _mark(f"verify {name}")
            _verify(small[name], sample_by_name.get(name, sample))
            _mark(f"time {name}")
            dt = _time(fn)
            gbps = data_bytes / dt / 1e9
            detail[name] = round(gbps, 3)
            if gbps > best[1]:
                best = (name, gbps)
                # Publish to the wedge watchdog IMMEDIATELY: a wedge while
                # timing the next strategy must not discard this verified
                # number (the strategies run fastest-first, so the first
                # success is usually the headline).  A SNAPSHOT of detail —
                # the watchdog thread must never iterate the live dict the
                # main thread keeps mutating.
                _PARTIAL = (backend, best, dict(detail))
        except Exception as e:
            detail[name] = f"failed: {type(e).__name__}"
        if fast_child and best[0] is not None:
            _mark("hardware child: headline strategy landed; skipping the rest")
            break
    _mark(f"strategies done: {detail}")

    if best[0] is None:
        # Even total strategy failure must leave the JSON line (the round's
        # one machine-readable artifact) with the failure recorded.
        _emit(backend, 0.0, {"error": "all strategies failed", **detail})
        raise SystemExit(1)

    # 4-erasure recovery latency (BASELINE's second headline): reconstruct
    # the P lost natives from the surviving k chunks with the best strategy.
    from gpu_rscode_tpu.models.vandermonde import total_matrix
    from gpu_rscode_tpu.ops.inverse import invert_matrix

    T = total_matrix(P, K)
    surv = list(range(P, P + K))
    inv_missing = invert_matrix(T[surv])[:P]  # only the lost rows
    survivors_host = np.concatenate(
        [B_host[P:], native.gemm(T[K:], B_host)], axis=0
    )[:K]
    if best[0] != "native":  # the native path never touches the device
        survivors = jax.device_put(survivors_host)
    if best[0] == "pallas":
        def run_decode():
            return gf_matmul_pallas(jax.device_put(inv_missing), survivors)
    elif best[0] == "native":
        def run_decode():
            return native.gemm(inv_missing, survivors_host)
    else:
        def run_decode():
            outs = [
                gf_matmul_jit(
                    jax.device_put(inv_missing),
                    survivors[:, off : off + seg],
                    strategy=best[0],
                )
                for off in range(0, m, seg)
            ]
            return jax.numpy.concatenate(outs, axis=1) if len(outs) > 1 else outs[0]

    try:
        _mark("time decode")
        dec_dt = _time(run_decode)
        detail["decode_gbps"] = round(data_bytes / dec_dt / 1e9, 3)
        detail["recovery_latency_ms"] = round(1e3 * dec_dt, 2)
    except Exception as e:
        detail["decode"] = f"failed: {type(e).__name__}"
    # Stage attribution for the headline strategy (obs/profiler.py): one
    # extra profiled dispatch outside every timed region — where the
    # encode wall goes (pack/chain/unpack...).  Best-effort: the bench's
    # one JSON line must emit whether or not the profiler can run here.
    if best[0] in ("xor", "bitplane", "table"):
        try:
            from gpu_rscode_tpu.tools.xor_ab import _profiled_stages

            _mark("profile stages")
            st = _profiled_stages([best[0]], A, Bd, 8)
            if st:
                detail["stages"] = st[best[0]]
        except Exception:
            pass
    _mark("done")
    _PARTIAL = (backend, best, dict(detail))  # refresh: decode keys landed
    # (backend was relabelled "tpu" above whenever the devices are real TPU
    # chips, however the tunnel registers itself — this guard only fires for
    # genuine CPU fallbacks.  The child never runs its own retry loop.)
    if (
        backend != "tpu"
        and not os.environ.get("RS_BENCH_NO_FALLBACK")
        and _tpu_retry_until_deadline()
    ):
        return  # the forwarded TPU line is the bench's single output line
    if backend != "tpu":
        # A CPU line means the tunnel was down for this run, not that no TPU
        # number exists — attach the committed same-config hardware
        # evidence (paths + inlined headline).
        _attach_committed_evidence(detail)
    _emit(backend, best[1], {"strategy": best[0], **detail})


if __name__ == "__main__":
    try:
        main()
    except SystemExit:
        raise
    except KeyboardInterrupt:
        # An operator interrupt is not a bench failure: emit the always-there
        # JSON line for any log scraper, then let the interrupt status
        # propagate (ADVICE r2).
        _emit("error", 0.0, {"error": "KeyboardInterrupt"})
        raise
    except Exception as e:  # noqa: BLE001 — the JSON line must always appear
        _emit("error", 0.0, {"error": f"{type(e).__name__}: {e}"[:300]})
        sys.exit(1)
