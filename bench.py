"""Benchmark — encode GB/s at the BASELINE headline config (k=10, n=14).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "detail"}.
Baseline: the reference's published GPU encode bandwidth, 1356.835 MB/s
(Tesla C2050, design.tex:490; BASELINE.md) == 1.356835 GB/s.

Method: a (k=10, p=4) stripe resident on the device is encoded by each
available GEMM strategy (fused Pallas kernel first, then the XLA bit-plane
path segmented to bound HBM, then the table path); every strategy's output
is verified bit-exact against the native CPU oracle on a sample before its
time counts.  The reported number is the best verified strategy's
steady-state device throughput (file bytes / wall), comparable to the
reference's kernel-bandwidth figure (which likewise excludes PCIe copies).
"""

import json

import numpy as np

from gpu_rscode_tpu.tools._bench_timing import time_device_fn as _time

K, P = 10, 4
BASELINE_GBPS = 1.356835


def _verify(small_fn, oracle_slice):
    """Bit-exactness gate on a small slab (cheap: runs the strategy on the
    4 KB slice only, not the full stripe)."""
    got = np.asarray(small_fn())
    if not np.array_equal(got, oracle_slice):
        raise AssertionError("output mismatch vs CPU oracle")


def main() -> None:
    import jax

    from gpu_rscode_tpu import native
    from gpu_rscode_tpu.models.vandermonde import vandermonde_matrix
    from gpu_rscode_tpu.ops.gemm import gf_matmul_jit
    from gpu_rscode_tpu.ops.pallas_gemm import gf_matmul_pallas

    backend = jax.default_backend()
    on_tpu = backend == "tpu"
    m = (32 * 1024 * 1024) if on_tpu else (2 * 1024 * 1024)  # bytes per chunk
    seg = 4 * 1024 * 1024  # XLA bitplane segment (bounds HBM expansion)

    A = vandermonde_matrix(P, K)
    rng = np.random.default_rng(0)
    B_host = rng.integers(0, 256, size=(K, m), dtype=np.uint8)
    Ad = jax.device_put(A)
    Bd = jax.device_put(B_host)
    sample = native.gemm(A, B_host[:, :4096])  # CPU-oracle verification slab
    Bd_small = jax.device_put(B_host[:, :4096])

    def run_pallas():
        return gf_matmul_pallas(Ad, Bd)

    def run_bitplane():
        outs = [
            gf_matmul_jit(Ad, Bd[:, off : off + seg], strategy="bitplane")
            for off in range(0, m, seg)
        ]
        return jax.numpy.concatenate(outs, axis=1) if len(outs) > 1 else outs[0]

    def run_table():
        outs = [
            gf_matmul_jit(Ad, Bd[:, off : off + seg], strategy="table")
            for off in range(0, m, seg)
        ]
        return jax.numpy.concatenate(outs, axis=1) if len(outs) > 1 else outs[0]

    small = {
        "pallas": lambda: gf_matmul_pallas(Ad, Bd_small),
        "bitplane": lambda: gf_matmul_jit(Ad, Bd_small, strategy="bitplane"),
        "table": lambda: gf_matmul_jit(Ad, Bd_small, strategy="table"),
    }
    candidates = [("pallas", run_pallas), ("bitplane", run_bitplane), ("table", run_table)]
    data_bytes = K * m
    detail = {}
    best = (None, 0.0)
    for name, fn in candidates:
        try:
            _verify(small[name], sample)
            dt = _time(fn)
            gbps = data_bytes / dt / 1e9
            detail[name] = round(gbps, 3)
            if gbps > best[1]:
                best = (name, gbps)
        except Exception as e:
            detail[name] = f"failed: {type(e).__name__}"

    if best[0] is None:
        raise SystemExit(f"all strategies failed: {detail}")

    # 4-erasure recovery latency (BASELINE's second headline): reconstruct
    # the P lost natives from the surviving k chunks with the best strategy.
    from gpu_rscode_tpu.models.vandermonde import total_matrix
    from gpu_rscode_tpu.ops.inverse import invert_matrix

    T = total_matrix(P, K)
    surv = list(range(P, P + K))
    inv_missing = invert_matrix(T[surv])[:P]  # only the lost rows
    survivors = jax.device_put(
        np.concatenate([B_host[P:], native.gemm(T[K:], B_host)], axis=0)[: K]
    )
    if best[0] == "pallas":
        def run_decode():
            return gf_matmul_pallas(jax.device_put(inv_missing), survivors)
    else:
        def run_decode():
            outs = [
                gf_matmul_jit(
                    jax.device_put(inv_missing),
                    survivors[:, off : off + seg],
                    strategy=best[0],
                )
                for off in range(0, m, seg)
            ]
            return jax.numpy.concatenate(outs, axis=1) if len(outs) > 1 else outs[0]

    try:
        dec_dt = _time(run_decode)
        detail["decode_gbps"] = round(data_bytes / dec_dt / 1e9, 3)
        detail["recovery_latency_ms"] = round(1e3 * dec_dt, 2)
    except Exception as e:
        detail["decode"] = f"failed: {type(e).__name__}"
    print(
        json.dumps(
            {
                "metric": f"encode_bandwidth_k{K}_n{K + P}_{backend}",
                "value": round(best[1], 3),
                "unit": "GB/s",
                "vs_baseline": round(best[1] / BASELINE_GBPS, 2),
                "detail": {"strategy": best[0], **detail},
            }
        )
    )


if __name__ == "__main__":
    main()
