"""Benchmark — encode GB/s at the BASELINE headline config (k=10, n=14).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Baseline: the reference's published GPU encode bandwidth, 1356.835 MB/s
(Tesla C2050, design.tex:490; BASELINE.md) == 1.356835 GB/s.

Runs on whatever jax.default_backend() provides (the driver runs it on one
real TPU chip).  Measures steady-state device-side encode throughput
(file bytes / wall time) over a resident stripe, after one warmup for
compile — comparable to the reference's "encoding file" kernel bandwidth
measurement, which also excludes PCIe copies from its MB/s figure.
"""

import json
import time

import numpy as np


def main() -> None:
    import jax

    from gpu_rscode_tpu.models.vandermonde import vandermonde_matrix
    from gpu_rscode_tpu.ops.gemm import gf_matmul_jit

    k, p = 10, 4
    m = 64 * 1024 * 1024  # 64 MiB per chunk -> 640 MiB data per stripe
    backend = jax.default_backend()
    if backend == "cpu":  # keep CI/dev runs fast; the driver uses the TPU
        m = 4 * 1024 * 1024

    A = jax.numpy.asarray(vandermonde_matrix(p, k))
    rng = np.random.default_rng(0)
    B = jax.device_put(rng.integers(0, 256, size=(k, m), dtype=np.uint8))

    def run():
        return gf_matmul_jit(A, B, strategy="bitplane")

    run().block_until_ready()  # warmup/compile
    iters = 10 if backend != "cpu" else 3
    t0 = time.perf_counter()
    for _ in range(iters):
        out = run()
    out.block_until_ready()
    dt = (time.perf_counter() - t0) / iters

    data_bytes = k * m  # the file bytes encoded per stripe
    gbps = data_bytes / dt / 1e9
    baseline_gbps = 1.356835
    print(
        json.dumps(
            {
                "metric": f"encode_bandwidth_k{k}_n{k + p}_{backend}",
                "value": round(gbps, 3),
                "unit": "GB/s",
                "vs_baseline": round(gbps / baseline_gbps, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
