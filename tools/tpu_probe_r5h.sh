#!/bin/bash
# Round-5h: combined retry of the two outstanding capture sets after the
# r5f watcher expired at its 8 h deadline without one healthy probe (the
# outage that started ~08:40 UTC).  Priority order: the autotune
# validation first (it validates shipped code — two w16 compile coin
# flips plus a w8 sanity run), then the bimodality map's t32768 cells
# (grid completeness only).  Each set retries across healthy windows
# until it lands whole.
# Usage: tools/tpu_probe_r5h.sh [max_seconds]
set -u
LIB="$(cd "$(dirname "$0")" && pwd)/capture_lib.sh"
cd /root/repo
mkdir -p bench_captures
MAX=${1:-36000}
START=$SECONDS
ATTEMPT=0
. "$LIB"

while pgrep -f "tpu_probe_r5[bcdefg]?[.]sh" >/dev/null 2>&1; do
  echo "# waiting for earlier r5 watchers t=$((SECONDS - START))s" >&2
  sleep 60
  [ $((SECONDS - START)) -ge "$MAX" ] && { echo "# deadline" >&2; exit 2; }
done

at_a=0; at_b=0; at_w8=0; t32_a=0; t32_b=0
while [ $((SECONDS - START)) -lt "$MAX" ]; do
  ATTEMPT=$((ATTEMPT + 1))
  echo "# probe $ATTEMPT t=$((SECONDS - START))s" >&2
  if timeout 75 python - <<'EOF' >/dev/null 2>&1
import sys
import jax
sys.exit(0 if any(d.platform.lower() == "tpu" for d in jax.devices()) else 1)
EOF
  then
    echo "# tunnel healthy (a=$at_a b=$at_b w8=$at_w8 t32a=$t32_a t32b=$t32_b)" >&2
    [ "$at_a" -eq 0 ] && capture w16_autotune_a 420 \
      env RS_PALLAS_REFOLD=autotune \
      python -m gpu_rscode_tpu.tools.w16_bench --trials 2 --mb 128 \
      && at_a=1
    [ "$at_b" -eq 0 ] && capture w16_autotune_b 420 \
      env RS_PALLAS_REFOLD=autotune \
      python -m gpu_rscode_tpu.tools.w16_bench --trials 2 --mb 128 \
      && at_b=1
    [ "$at_w8" -eq 0 ] && capture w8_autotune_k10 600 \
      env RS_PALLAS_REFOLD=autotune \
      python -m gpu_rscode_tpu.tools.expand_probe --trials 3 \
      --expand shift_raw --acc int8 \
      && at_w8=1
    [ "$t32_a" -eq 0 ] && capture w16_bimodal_t32768_a_retry 420 \
      env RS_PALLAS_EXPAND=shift_raw RS_PALLAS_REFOLD=dot \
      RS_PALLAS_TILE=32768 \
      python -m gpu_rscode_tpu.tools.w16_bench --trials 2 --mb 128 \
      && t32_a=1
    [ "$t32_b" -eq 0 ] && capture w16_bimodal_t32768_b_retry 420 \
      env RS_PALLAS_EXPAND=shift_raw RS_PALLAS_REFOLD=dot \
      RS_PALLAS_TILE=32768 \
      python -m gpu_rscode_tpu.tools.w16_bench --trials 2 --mb 128 \
      && t32_b=1
    if [ $((at_a + at_b + at_w8 + t32_a + t32_b)) -eq 5 ]; then
      echo "# r5h complete" >&2
      exit 0
    fi
    echo "# incomplete set (wedge?); backing off before retry" >&2
    sleep 300
  else
    sleep 120
  fi
done
echo "# deadline; landed a=$at_a b=$at_b w8=$at_w8 t32a=$t32_a t32b=$t32_b" >&2
exit 2
