#!/bin/bash
# Round-5 gap fillers: the post-flip tile sweep's two missing k=10 points
# (8192, 65536 under shift_raw+dot+int8).  The first 65536 attempt hung at
# jax init / first compile and the tunnel wedged at ~2026-08-01 00:52 UTC
# (tile_dot_k10_t65536_int8_tpu_20260801T005229Z.log shows no output past
# the backend-init warning), so both points are unmeasured.  Low stakes:
# the shipped default (16384) measured within noise of 32768 and these
# only bound the tile curve's tails.  Keeps retrying failed points across
# healthy windows until both land or the deadline passes (a wedge mid-set
# must not report success).
# Usage: tools/tpu_probe_r5d.sh [max_seconds]
set -u
LIB="$(cd "$(dirname "$0")" && pwd)/capture_lib.sh"
cd /root/repo
mkdir -p bench_captures
MAX=${1:-36000}
START=$SECONDS
ATTEMPT=0
. "$LIB"

while pgrep -f "tpu_probe_r5[bc]?[.]sh" >/dev/null 2>&1; do
  echo "# waiting for earlier r5 watchers t=$((SECONDS - START))s" >&2
  sleep 60
  [ $((SECONDS - START)) -ge "$MAX" ] && { echo "# deadline" >&2; exit 2; }
done

done_8192=0
done_65536=0
while [ $((SECONDS - START)) -lt "$MAX" ]; do
  ATTEMPT=$((ATTEMPT + 1))
  echo "# probe $ATTEMPT t=$((SECONDS - START))s" >&2
  if timeout 75 python - <<'EOF' >/dev/null 2>&1
import sys
import jax
sys.exit(0 if any(d.platform.lower() == "tpu" for d in jax.devices()) else 1)
EOF
  then
    echo "# tunnel healthy; r5d gap fillers (8192=$done_8192 65536=$done_65536)" >&2
    P=(python -m gpu_rscode_tpu.tools.expand_probe --trials 3
       --expand shift_raw --refold dot --acc int8)
    if [ "$done_8192" -eq 0 ]; then
      capture tile_dot_k10_t8192_int8_retry 600 "${P[@]}" --tile 8192 \
        && done_8192=1
    fi
    if [ "$done_65536" -eq 0 ]; then
      capture tile_dot_k10_t65536_int8_retry 600 "${P[@]}" --tile 65536 \
        && done_65536=1
    fi
    if [ "$done_8192" -eq 1 ] && [ "$done_65536" -eq 1 ]; then
      echo "# r5d gap fillers complete" >&2
      exit 0
    fi
    echo "# incomplete set (wedge?); backing off before retry" >&2
    sleep 300
  else
    sleep 120
  fi
done
echo "# deadline reached; landed 8192=$done_8192 65536=$done_65536" >&2
exit 2
