#!/bin/bash
# Round-5 gap fillers: the post-flip tile sweep's two missing k=10 points
# (65536, 8192 under shift_raw+dot+int8).  The first 65536 attempt hung at
# jax init / first compile and the tunnel wedged at ~2026-08-01 00:52 UTC
# (tile_dot_k10_t65536_int8_tpu_20260801T005229Z.log shows no output past
# the backend-init warning), so both points are unmeasured.  Low stakes:
# the shipped default (16384) measured within noise of 32768 and these
# only bound the tile curve's tails.
# Usage: tools/tpu_probe_r5d.sh [max_seconds]
set -u
LIB="$(cd "$(dirname "$0")" && pwd)/capture_lib.sh"
cd /root/repo
mkdir -p bench_captures
MAX=${1:-36000}
START=$SECONDS
ATTEMPT=0
. "$LIB"

while pgrep -f "tpu_probe_r5[bc]?[.]sh" >/dev/null 2>&1; do
  echo "# waiting for earlier r5 watchers t=$((SECONDS - START))s" >&2
  sleep 60
  [ $((SECONDS - START)) -ge "$MAX" ] && { echo "# deadline" >&2; exit 2; }
done

while [ $((SECONDS - START)) -lt "$MAX" ]; do
  ATTEMPT=$((ATTEMPT + 1))
  echo "# probe $ATTEMPT t=$((SECONDS - START))s" >&2
  if timeout 75 python - <<'EOF' >/dev/null 2>&1
import sys
import jax
sys.exit(0 if any(d.platform.lower() == "tpu" for d in jax.devices()) else 1)
EOF
  then
    echo "# tunnel healthy; starting r5d gap fillers" >&2
    P=(python -m gpu_rscode_tpu.tools.expand_probe --trials 3
       --expand shift_raw --refold dot --acc int8)
    capture tile_dot_k10_t8192_int8_retry 600 "${P[@]}" --tile 8192
    capture tile_dot_k10_t65536_int8_retry 600 "${P[@]}" --tile 65536
    echo "# r5d gap fillers complete" >&2
    exit 0
  fi
  sleep 120
done
echo "# deadline reached without healthy tunnel" >&2
exit 2
