#!/bin/bash
# TPU capture loop — round-3 response to VERDICT item 1 ("treat the tunnel as
# intermittent, not binary").  Probes the axon tunnel every ~2 min; the moment
# a probe comes back healthy it captures bench.py and the kernel sweep into
# timestamped files under bench_captures/ and exits 0 so the operator can
# commit them.  Exits 2 on deadline without a healthy probe.
#
# Usage: tools/tpu_capture.sh [max_seconds] [--bench-only]
set -u
cd /root/repo
mkdir -p bench_captures
MAX=36000
MODE=full
for arg in "$@"; do
  case "$arg" in
    --bench-only) MODE=--bench-only ;;
    *[!0-9]*) echo "unknown arg: $arg" >&2; exit 64 ;;
    *) MAX=$arg ;;
  esac
done
START=$SECONDS
ATTEMPT=0
while [ $((SECONDS - START)) -lt "$MAX" ]; do
  ATTEMPT=$((ATTEMPT + 1))
  echo "# probe $ATTEMPT t=$((SECONDS - START))s" >&2
  if timeout 90 python - <<'EOF' >/dev/null 2>&1
import sys
import jax
sys.exit(0 if any(d.platform.lower() == "tpu" for d in jax.devices()) else 1)
EOF
  then
    ts=$(date -u +%Y%m%dT%H%M%SZ)
    echo "# tunnel healthy at $ts; capturing" >&2
    timeout 1200 python bench.py \
      > "bench_captures/bench_${ts}.json" 2> "bench_captures/bench_${ts}.log"
    brc=$?
    if [ $brc -eq 0 ] && grep -q '_tpu"' "bench_captures/bench_${ts}.json"; then
      # The bench_tpu_ prefix is what bench.py's committed-capture pointer
      # globs for (bench.py _committed_tpu_captures) — keep them findable.
      mv "bench_captures/bench_${ts}.json" "bench_captures/bench_tpu_${ts}.json"
      echo "# bench capture OK: bench_captures/bench_tpu_${ts}.json" >&2
      if [ "$MODE" = "--bench-only" ]; then exit 0; fi
      timeout 1800 python -m gpu_rscode_tpu.tools.kernel_sweep --mb 64 --trials 2 \
        > "bench_captures/sweep_${ts}.json" 2> "bench_captures/sweep_${ts}.log"
      src=$?
      echo "# sweep rc=$src" >&2
      exit 0
    fi
    echo "# bench rc=$brc but no TPU line; keep looping" >&2
  fi
  sleep 120
done
echo "# deadline reached without healthy tunnel" >&2
exit 2
