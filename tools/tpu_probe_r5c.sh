#!/bin/bash
# Round-5 follow-up captures raised by the session-2 results:
#   1. w16 refold crossover — the r5 set resolved the "hang" (it was the
#      tunnel: both small-shape runs returned rc=0) but showed the w16
#      refold optimum is SHAPE-dependent: sum wins at 32 MB (19.2 vs 8.2)
#      while dot wins at 320 MB (147.0 vs 101.9,
#      w16_raw_dot_full_tpu_20260801T001620Z).  Probe 64/128/192 MB for
#      both refolds to place the crossover before flipping any default.
#   2. dma_floor re-measure — the post-flip floors run read 125.1 GB/s
#      where the r3 capture read 286 at the same 320 MB shape; one is
#      chip/tunnel state.  Three spaced re-reads disambiguate.
# Waits for the main r5 set (one tunnel client at a time).
# Usage: tools/tpu_probe_r5c.sh [max_seconds]
set -u
LIB="$(cd "$(dirname "$0")" && pwd)/capture_lib.sh"
cd /root/repo
mkdir -p bench_captures
MAX=${1:-36000}
START=$SECONDS
ATTEMPT=0
. "$LIB"

while pgrep -f "tpu_probe_r5b?[.]sh" >/dev/null 2>&1; do
  echo "# waiting for the main r5 capture set t=$((SECONDS - START))s" >&2
  sleep 60
  [ $((SECONDS - START)) -ge "$MAX" ] && { echo "# deadline" >&2; exit 2; }
done

while [ $((SECONDS - START)) -lt "$MAX" ]; do
  ATTEMPT=$((ATTEMPT + 1))
  echo "# probe $ATTEMPT t=$((SECONDS - START))s" >&2
  if timeout 75 python - <<'EOF' >/dev/null 2>&1
import sys
import jax
sys.exit(0 if any(d.platform.lower() == "tpu" for d in jax.devices()) else 1)
EOF
  then
    echo "# tunnel healthy; starting r5c follow-up set" >&2

    W16=(python -m gpu_rscode_tpu.tools.w16_bench --trials 2)
    for mb in 64 128 192; do
      capture "w16_cross_sum_mb${mb}" 420 \
        env RS_PALLAS_EXPAND=shift_raw RS_PALLAS_REFOLD=sum \
        "${W16[@]}" --mb "$mb"
      capture "w16_cross_dot_mb${mb}" 420 \
        env RS_PALLAS_EXPAND=shift_raw RS_PALLAS_REFOLD=dot \
        "${W16[@]}" --mb "$mb"
    done

    for i in 1 2 3; do
      capture "dma_floor_recheck_$i" 600 \
        python -m gpu_rscode_tpu.tools.kernel_sweep \
        --mb 320 --trials 3 --bodies raw_dot --tiles 32768
      sleep 30
    done

    echo "# r5c follow-up set complete" >&2
    exit 0
  fi
  sleep 60
done
echo "# deadline reached without healthy tunnel" >&2
exit 2
