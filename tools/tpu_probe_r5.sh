#!/bin/bash
# Round-5 capture set, in VERDICT-r4 priority order.  Waits for any
# round-4 watcher still armed (one tunnel client at a time), then on the
# first healthy probe captures, committing after EVERY capture:
#   1. bench.py headline — the driver-identical artifact under the
#      shift_raw+dot production defaults (VERDICT r4 task 1: the round
#      artifact has carried a CPU fallback for four rounds).
#   2. mesh_bench — fused kernel under shard_map on a real-chip mesh
#      (task 2: cols + stripe-psum + the pre-parity kernel form; a Mosaic
#      refusal propagates and the committed log is the deliverable).
#   3. kernel floors under shift_raw+dot (task 3: the 102.5 GB/s headline
#      is past the OLD 64.9 compute ceiling; optimization is blind
#      without a fresh floor).
#   4. w16 refold disambiguation at SMALL shape + SHORT timeout (task 4:
#      the one w16+dot attempt died at a 900 s timeout with the tunnel
#      wedging right after; a 240 s small-shape run separates hang from
#      tunnel quickly and cheaply).  sum first (baseline), dot last.
#   5. inverse_bench --pivot both (task 5: the no-pivot batched inverse
#      vs the pivoting one vs the host loop, k in {10,32,64,128} — sets
#      or retires _DEVICE_INVERT_MAX_K_TPU from measurement).
#   6. nibble32 verdict + tile x acc micro-sweep at the headline shape
#      (task 3 follow-ups, inherited from the r4e watcher).
#   7. k_sweep rerun under the new defaults.
# Usage: tools/tpu_probe_r5.sh [max_seconds]
set -u
LIB="$(cd "$(dirname "$0")" && pwd)/capture_lib.sh"
cd /root/repo
mkdir -p bench_captures
MAX=${1:-40000}
START=$SECONDS
ATTEMPT=0
. "$LIB"

while pgrep -f "tpu_probe_r4[a-f].sh" >/dev/null 2>&1; do
  echo "# waiting for round-4 watchers to finish t=$((SECONDS - START))s" >&2
  sleep 60
  [ $((SECONDS - START)) -ge "$MAX" ] && { echo "# deadline" >&2; exit 2; }
done

while [ $((SECONDS - START)) -lt "$MAX" ]; do
  ATTEMPT=$((ATTEMPT + 1))
  echo "# probe $ATTEMPT t=$((SECONDS - START))s" >&2
  if timeout 75 python - <<'EOF' >/dev/null 2>&1
import sys
import jax
sys.exit(0 if any(d.platform.lower() == "tpu" for d in jax.devices()) else 1)
EOF
  then
    echo "# tunnel healthy; starting round-5 capture set" >&2

    # 1. Headline bench (promotion convention lives in capture_lib.sh).
    capture_bench 900

    # 2. shard_map lowering proof on the real chip.
    capture mesh_pallas 900 \
      python -m gpu_rscode_tpu.tools.mesh_bench --mb 320 --trials 3

    # 3. Post-flip kernel floors (the r4f payload).
    capture kernel_floors_postflip 1200 \
      python -m gpu_rscode_tpu.tools.kernel_sweep \
      --mb 320 --trials 3 --bodies base,raw_dot --tiles 16384,32768

    # 4. w16 hang disambiguation: tiny shape, short timeout, sum first.
    W16S=(python -m gpu_rscode_tpu.tools.w16_bench --mb 32 --trials 1)
    capture w16_small_sum 240 \
      env RS_PALLAS_EXPAND=shift_raw RS_PALLAS_REFOLD=sum "${W16S[@]}"
    capture w16_small_dot 240 \
      env RS_PALLAS_EXPAND=shift_raw RS_PALLAS_REFOLD=dot "${W16S[@]}"
    # Full-shape dot only if the small-shape run survived (rc!=124).
    if [ $? -ne 124 ]; then
      capture w16_raw_dot_full 900 \
        env RS_PALLAS_EXPAND=shift_raw RS_PALLAS_REFOLD=dot \
        python -m gpu_rscode_tpu.tools.w16_bench --trials 3
    fi

    # 5. Batched-inversion routing: pivot vs no-pivot vs host across k.
    capture inverse_nopivot 900 \
      python -m gpu_rscode_tpu.tools.inverse_bench \
      --k 10 32 64 128 --batch 16 64 256 1024

    # 6. nibble32 verdict + tile/acc micro-sweep (the r4e payload).
    P=(python -m gpu_rscode_tpu.tools.expand_probe --trials 3)
    capture nibble32_k10 900 "${P[@]}" --expand shift_raw nibble32
    for tile in 16384 32768; do
      for acc in int8 bf16; do
        capture "tile_dot_k10_t${tile}_${acc}" 600 "${P[@]}" \
          --expand shift_raw --refold dot --tile "$tile" --acc "$acc"
      done
    done

    # 7. k-sweep under the production defaults.
    capture k_sweep_postflip 1800 python -m gpu_rscode_tpu.tools.k_sweep

    echo "# round-5 capture set complete" >&2
    exit 0
  fi
  sleep 60
done
echo "# deadline reached without healthy tunnel" >&2
exit 2
