#!/bin/bash
# Round-5b: gap-filler behind tpu_probe_r5.sh.  The r4 outage pattern is
# a window that closes MID-SET — r5 runs its list once and exits, so a
# later window would find nothing armed.  This watcher waits for r5 to
# finish, then on each healthy probe re-captures ONLY the priority
# artifacts that do not exist yet (fresh bench_tpu_* from today counts as
# existing), in the same order.  Repeats until everything exists or the
# deadline passes.
# Usage: tools/tpu_probe_r5b.sh [max_seconds]
set -u
LIB="$(cd "$(dirname "$0")" && pwd)/capture_lib.sh"
cd /root/repo
mkdir -p bench_captures
MAX=${1:-40000}
START=$SECONDS
ATTEMPT=0
. "$LIB"

TODAY=$(date -u +%Y%m%d)

have() { compgen -G "bench_captures/$1" >/dev/null; }

# A bench from today after 14:00 UTC counts as the fresh post-flip
# headline (the r5 watcher was armed ~13:40 UTC).
fresh_bench() {
  have "bench_tpu_${TODAY}T1[4-9]*.json" || have "bench_tpu_${TODAY}T2*.json"
}

# True (rc 0) iff ANY priority artifact is still missing.
missing_any() {
  ! fresh_bench \
    || ! have "mesh_pallas_tpu_*.jsonl" \
    || ! have "kernel_floors_postflip_tpu_*.jsonl" \
    || ! have "w16_small_dot_tpu_*.jsonl" \
    || ! have "inverse_nopivot_tpu_*.jsonl" \
    || ! have "nibble32_k10_tpu_*.jsonl" \
    || ! have "k_sweep_postflip_tpu_*.jsonl"
}

while pgrep -f "tools/tpu_probe_r5.sh" >/dev/null 2>&1; do
  echo "# waiting for r5 to finish t=$((SECONDS - START))s" >&2
  sleep 120
  [ $((SECONDS - START)) -ge "$MAX" ] && { echo "# deadline" >&2; exit 2; }
done

while [ $((SECONDS - START)) -lt "$MAX" ]; do
  ATTEMPT=$((ATTEMPT + 1))
  echo "# probe $ATTEMPT t=$((SECONDS - START))s" >&2
  if timeout 75 python - <<'EOF' >/dev/null 2>&1
import sys
import jax
sys.exit(0 if any(d.platform.lower() == "tpu" for d in jax.devices()) else 1)
EOF
  then
    echo "# tunnel healthy; filling round-5 capture gaps" >&2
    fresh_bench || capture_bench 900
    have "mesh_pallas_tpu_*.jsonl" || capture mesh_pallas 900 \
      python -m gpu_rscode_tpu.tools.mesh_bench --mb 320 --trials 3
    have "kernel_floors_postflip_tpu_*.jsonl" || \
      capture kernel_floors_postflip 1200 \
      python -m gpu_rscode_tpu.tools.kernel_sweep \
      --mb 320 --trials 3 --bodies base,raw_dot --tiles 16384,32768
    if ! have "w16_small_dot_tpu_*.jsonl"; then
      W16S=(python -m gpu_rscode_tpu.tools.w16_bench --mb 32 --trials 1)
      capture w16_small_sum 240 \
        env RS_PALLAS_EXPAND=shift_raw RS_PALLAS_REFOLD=sum "${W16S[@]}"
      capture w16_small_dot 240 \
        env RS_PALLAS_EXPAND=shift_raw RS_PALLAS_REFOLD=dot "${W16S[@]}"
    fi
    have "inverse_nopivot_tpu_*.jsonl" || capture inverse_nopivot 900 \
      python -m gpu_rscode_tpu.tools.inverse_bench \
      --k 10 32 64 128 --batch 16 64 256 1024
    have "nibble32_k10_tpu_*.jsonl" || capture nibble32_k10 900 \
      python -m gpu_rscode_tpu.tools.expand_probe --trials 3 \
      --expand shift_raw nibble32
    have "k_sweep_postflip_tpu_*.jsonl" || capture k_sweep_postflip 1800 \
      python -m gpu_rscode_tpu.tools.k_sweep
    if ! missing_any; then
      echo "# all round-5 priority artifacts exist; done" >&2
      exit 0
    fi
    echo "# window pass complete; some artifacts still missing" >&2
  fi
  sleep 60
done
echo "# deadline reached without completing the capture set" >&2
exit 2
