#!/bin/bash
# Round-5g: the bimodality map's last cell — tile 32768 x 2 processes at
# w=16 mb=128 (both r5e attempts died rc=124 when the tunnel closed
# ~08:40 UTC mid-map).  The map's verdict (compile-time nondeterminism,
# not tile dependence) is already pinned by tiles 8192/16384; this only
# completes the grid.  Runs after the r5f autotune validation.
# Usage: tools/tpu_probe_r5g.sh [max_seconds]
set -u
LIB="$(cd "$(dirname "$0")" && pwd)/capture_lib.sh"
cd /root/repo
mkdir -p bench_captures
MAX=${1:-36000}
START=$SECONDS
ATTEMPT=0
. "$LIB"

while pgrep -f "tpu_probe_r5[bcdef]?[.]sh" >/dev/null 2>&1; do
  echo "# waiting for earlier r5 watchers t=$((SECONDS - START))s" >&2
  sleep 60
  [ $((SECONDS - START)) -ge "$MAX" ] && { echo "# deadline" >&2; exit 2; }
done

while [ $((SECONDS - START)) -lt "$MAX" ]; do
  ATTEMPT=$((ATTEMPT + 1))
  echo "# probe $ATTEMPT t=$((SECONDS - START))s" >&2
  if timeout 75 python - <<'EOF' >/dev/null 2>&1
import sys
import jax
sys.exit(0 if any(d.platform.lower() == "tpu" for d in jax.devices()) else 1)
EOF
  then
    echo "# tunnel healthy; t32768 map cells" >&2
    for rep in a b; do
      capture "w16_bimodal_t32768_${rep}_retry" 420 \
        env RS_PALLAS_EXPAND=shift_raw RS_PALLAS_REFOLD=dot \
        RS_PALLAS_TILE=32768 \
        python -m gpu_rscode_tpu.tools.w16_bench --trials 2 --mb 128
    done
    echo "# r5g map cells complete" >&2
    exit 0
  fi
  sleep 120
done
echo "# deadline reached without healthy tunnel" >&2
exit 2
