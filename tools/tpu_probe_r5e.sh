#!/bin/bash
# Round-5e: map the w16+dot bimodality (82-148 GB/s across same-shape
# processes; sum is stable ~102 — w16_cross_*_tpu_20260801T*).  Each slow
# reading was a best-of-trials WITHIN one process, so the mode is set at
# (re)compile time, not per-dispatch.  This probe asks whether the mode is
# tile-dependent: 2 separate processes per tile in {8192, 16384, 32768}
# at mb=128.  A tile that lands fast on both runs is a candidate stable
# default that would ship ~147 GB/s for GF(2^16); all-tiles-bimodal pins
# the cause on remote-toolchain compile nondeterminism (document, keep
# sum).  Runs after the r5d gap fillers.
# Usage: tools/tpu_probe_r5e.sh [max_seconds]
set -u
LIB="$(cd "$(dirname "$0")" && pwd)/capture_lib.sh"
cd /root/repo
mkdir -p bench_captures
MAX=${1:-36000}
START=$SECONDS
ATTEMPT=0
. "$LIB"

while pgrep -f "tpu_probe_r5[bcd]?[.]sh" >/dev/null 2>&1; do
  echo "# waiting for earlier r5 watchers t=$((SECONDS - START))s" >&2
  sleep 60
  [ $((SECONDS - START)) -ge "$MAX" ] && { echo "# deadline" >&2; exit 2; }
done

while [ $((SECONDS - START)) -lt "$MAX" ]; do
  ATTEMPT=$((ATTEMPT + 1))
  echo "# probe $ATTEMPT t=$((SECONDS - START))s" >&2
  if timeout 75 python - <<'EOF' >/dev/null 2>&1
import sys
import jax
sys.exit(0 if any(d.platform.lower() == "tpu" for d in jax.devices()) else 1)
EOF
  then
    echo "# tunnel healthy; starting w16 bimodality tile map" >&2
    for tile in 8192 16384 32768; do
      for rep in a b; do
        capture "w16_bimodal_t${tile}_${rep}" 420 \
          env RS_PALLAS_EXPAND=shift_raw RS_PALLAS_REFOLD=dot \
          RS_PALLAS_TILE="$tile" \
          python -m gpu_rscode_tpu.tools.w16_bench --trials 2 --mb 128
      done
    done
    echo "# r5e bimodality map complete" >&2
    exit 0
  fi
  sleep 120
done
echo "# deadline reached without healthy tunnel" >&2
exit 2
