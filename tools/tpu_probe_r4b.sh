#!/bin/bash
# Round-4b follow-up probe set: hardware verdicts for the two new kernel
# formulations (shift_raw expansion, MXU dot refold) at the headline
# (k=10, int8@16384) and deep (k=64, bf16@32768) operating points, plus
# the decode shape (p=k).  Commits after every capture — same convention
# as tpu_capture_r4.sh.  Run only when the tunnel is otherwise idle.
set -u
LIB="$(cd "$(dirname "$0")" && pwd)/capture_lib.sh"
cd /root/repo
mkdir -p bench_captures
START=$SECONDS

. "$LIB"

P=(python -m gpu_rscode_tpu.tools.expand_probe --trials 3)
capture expand_r4b_k10 900 "${P[@]}" --expand shift shift_raw pack2
capture expand_r4b_k10_dot 900 "${P[@]}" --expand shift shift_raw --refold dot
capture expand_r4b_k64 900 "${P[@]}" --k 64 --expand shift shift_raw pack2
capture expand_r4b_k64_dot 900 "${P[@]}" --k 64 --expand shift shift_raw --refold dot
# Decode shape: square coefficient matrix (p = k)
capture expand_r4b_decode 900 "${P[@]}" --k 10 --p 10 --expand shift shift_raw pack2
capture expand_r4b_decode_dot 900 "${P[@]}" --k 10 --p 10 --expand shift shift_raw --refold dot
# Wedged-tunnel casualties from the r4 set, cheapest first; the stream
# bench goes LAST — its heavy host<->device transfer pattern over the
# tunnel is the likeliest wedge trigger.
capture inverse 900 python -m gpu_rscode_tpu.tools.inverse_bench
mkdir -p /dev/shm/rs_stream
capture stream_tmpfs 1200 python -m gpu_rscode_tpu.tools.stream_bench \
  --mb 256 --dir /dev/shm/rs_stream --seg-mb 64
rm -rf /dev/shm/rs_stream
echo "# round-4b probe set complete" >&2
