#!/bin/bash
# Round-4c follow-up: the capture points the r4b set could not deliver.
#
# The r4b session (expand_r4b_* captures, 2026-07-31) proved shift_raw >
# shift at every probed shape and, after the mid-session cast fix, that
# refold="dot" lowers and wins (k64: 132.0 vs 119.4; decode p=k=10:
# 80.5 vs 48.4).  The k=10 HEADLINE point with refold=dot failed pre-fix
# (f32->uint8 cast), and the wide-symbol (w=16) path has no
# shift_raw/dot capture yet.  pack2 has its verdict (correct after the
# Precision.HIGHEST fix, but 2.39 GB/s — the multi-pass MXU cost kills
# it; expand_r4b_decode capture) and is not re-probed.
# Commits after every capture — same convention as tpu_probe_r4b.sh.
set -u
LIB="$(cd "$(dirname "$0")" && pwd)/capture_lib.sh"
cd /root/repo
mkdir -p bench_captures
START=$SECONDS

. "$LIB"

P=(python -m gpu_rscode_tpu.tools.expand_probe --trials 3)
capture expand_r4c_k10_dot 900 "${P[@]}" --expand shift shift_raw --refold dot
capture expand_r4c_k128_dot 900 "${P[@]}" --k 128 --expand shift_raw --refold dot
W16=(python -m gpu_rscode_tpu.tools.w16_bench --trials 3)
capture w16_raw 900 env RS_PALLAS_EXPAND=shift_raw "${W16[@]}"
capture w16_raw_dot 900 env RS_PALLAS_EXPAND=shift_raw RS_PALLAS_REFOLD=dot "${W16[@]}"
echo "# round-4c probe set complete" >&2
