#!/bin/bash
# Round-5f: hardware validation of refold="autotune" (the operational
# answer to the w16 dot bimodality the r5e map pinned as compile-time
# nondeterminism).  Two separate w16 processes = two compile coin flips:
# each run's calibration must either ship the fast-dot mode (~132-147
# GB/s) or fall back to the stable sum (~102) — any reading >= ~95 GB/s
# validates the floor; a fast reading additionally demonstrates the
# upside.  One w8 headline-shape run sanity-checks that calibration
# agrees with the static default (dot) where dot always wins.
# Usage: tools/tpu_probe_r5f.sh [max_seconds]
set -u
LIB="$(cd "$(dirname "$0")" && pwd)/capture_lib.sh"
cd /root/repo
mkdir -p bench_captures
MAX=${1:-36000}
START=$SECONDS
ATTEMPT=0
. "$LIB"

while pgrep -f "tpu_probe_r5[bcde]?[.]sh" >/dev/null 2>&1; do
  echo "# waiting for earlier r5 watchers t=$((SECONDS - START))s" >&2
  sleep 60
  [ $((SECONDS - START)) -ge "$MAX" ] && { echo "# deadline" >&2; exit 2; }
done

while [ $((SECONDS - START)) -lt "$MAX" ]; do
  ATTEMPT=$((ATTEMPT + 1))
  echo "# probe $ATTEMPT t=$((SECONDS - START))s" >&2
  if timeout 75 python - <<'EOF' >/dev/null 2>&1
import sys
import jax
sys.exit(0 if any(d.platform.lower() == "tpu" for d in jax.devices()) else 1)
EOF
  then
    echo "# tunnel healthy; autotune validation set" >&2
    for rep in a b; do
      capture "w16_autotune_${rep}" 420 \
        env RS_PALLAS_REFOLD=autotune \
        python -m gpu_rscode_tpu.tools.w16_bench --trials 2 --mb 128
    done
    capture w8_autotune_k10 600 \
      env RS_PALLAS_REFOLD=autotune \
      python -m gpu_rscode_tpu.tools.expand_probe --trials 3 \
      --expand shift_raw --acc int8
    echo "# r5f autotune validation complete" >&2
    exit 0
  fi
  sleep 120
done
echo "# deadline reached without healthy tunnel" >&2
exit 2
