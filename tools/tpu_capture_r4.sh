#!/bin/bash
# Round-4 TPU capture orchestrator.  Probes the axon tunnel every ~1-2 min;
# on the first healthy probe it captures the round-4 evidence set in
# priority order, git-committing after EVERY capture (the tunnel can wedge
# mid-run at any point — r3 memory: capture the moment a probe succeeds,
# commit immediately):
#   1. bench.py headline            (VERDICT item 1)
#   2. expand_probe                 (items 2 + 8: expansion formulations)
#   3. k_sweep k in {4..128}        (item 5: k-scaling study)
#   4. w16_bench                    (item 5: wide-symbol hardware number)
#   5. stream_bench on tmpfs 1 GB   (item 6: device-resident end-to-end)
#   6. inverse_bench                (item 7: batched-inversion win)
# Usage: tools/tpu_capture_r4.sh [max_seconds]
set -u
cd /root/repo
mkdir -p bench_captures
MAX=${1:-36000}
START=$SECONDS
ATTEMPT=0

capture() {  # capture <name> <timeout> <cmd...>
  local name=$1 tmo=$2; shift 2
  local ts
  ts=$(date -u +%Y%m%dT%H%M%SZ)
  local out="bench_captures/${name}_tpu_${ts}.jsonl"
  echo "# [$((SECONDS - START))s] capturing ${name} (timeout ${tmo}s)" >&2
  timeout "$tmo" "$@" > "$out" 2> "${out%.jsonl}.log"
  local rc=$?
  echo "# ${name} rc=${rc}" >&2
  # commented-jsonl convention: '#'-prefix any human-readable lines a tool
  # printed to stdout (e.g. stream_bench phase summaries)
  sed -i -e '/^[{#]/!s/^/# /' "$out" 2>/dev/null
  if [ -s "$out" ]; then
    git add "$out" "${out%.jsonl}.log" 2>/dev/null
    git commit -q -m "TPU capture: ${name} (rc=${rc})" 2>/dev/null
  else
    rm -f "$out"
  fi
  return $rc
}

while [ $((SECONDS - START)) -lt "$MAX" ]; do
  ATTEMPT=$((ATTEMPT + 1))
  echo "# probe $ATTEMPT t=$((SECONDS - START))s" >&2
  if timeout 75 python - <<'EOF' >/dev/null 2>&1
import sys
import jax
sys.exit(0 if any(d.platform.lower() == "tpu" for d in jax.devices()) else 1)
EOF
  then
    echo "# tunnel healthy; starting round-4 capture set" >&2

    # 1. headline bench (bench_tpu_ prefix is what bench.py globs for)
    ts=$(date -u +%Y%m%dT%H%M%SZ)
    timeout 900 python bench.py \
      > "bench_captures/bench_${ts}.json" 2> "bench_captures/bench_${ts}.log"
    brc=$?
    if [ $brc -eq 0 ] && grep -q '_tpu"' "bench_captures/bench_${ts}.json"; then
      mv "bench_captures/bench_${ts}.json" "bench_captures/bench_tpu_${ts}.json"
      git add "bench_captures/bench_tpu_${ts}.json" "bench_captures/bench_${ts}.log"
      git commit -q -m "TPU capture: headline bench"
      echo "# bench capture OK" >&2
    else
      echo "# bench rc=$brc without TPU line; continuing with the tool set" >&2
      rm -f "bench_captures/bench_${ts}.json"
    fi

    capture expand_probe 1800 python -m gpu_rscode_tpu.tools.expand_probe
    capture k_sweep 2400 python -m gpu_rscode_tpu.tools.k_sweep
    capture w16 900 python -m gpu_rscode_tpu.tools.w16_bench
    mkdir -p /dev/shm/rs_stream
    capture stream_tmpfs 1800 python -m gpu_rscode_tpu.tools.stream_bench \
      --mb 1024 --dir /dev/shm/rs_stream --seg-mb 128
    rm -rf /dev/shm/rs_stream
    capture inverse 900 python -m gpu_rscode_tpu.tools.inverse_bench
    echo "# round-4 capture set complete" >&2
    exit 0
  fi
  sleep 45
done
echo "# deadline reached without healthy tunnel" >&2
exit 2
