#!/bin/bash
# Round-4 TPU capture orchestrator.  Probes the axon tunnel every ~1-2 min;
# on the first healthy probe it captures the round-4 evidence set in
# priority order, git-committing after EVERY capture (the tunnel can wedge
# mid-run at any point — r3 memory: capture the moment a probe succeeds,
# commit immediately):
#   1. bench.py headline            (VERDICT item 1)
#   2. expand_probe                 (items 2 + 8: expansion formulations)
#   3. k_sweep k in {4..128}        (item 5: k-scaling study)
#   4. w16_bench                    (item 5: wide-symbol hardware number)
#   5. stream_bench on tmpfs 1 GB   (item 6: device-resident end-to-end)
#   6. inverse_bench                (item 7: batched-inversion win)
# Usage: tools/tpu_capture_r4.sh [max_seconds]
set -u
LIB="$(cd "$(dirname "$0")" && pwd)/capture_lib.sh"
cd /root/repo
mkdir -p bench_captures
MAX=${1:-36000}
START=$SECONDS
ATTEMPT=0
. "$LIB"

while [ $((SECONDS - START)) -lt "$MAX" ]; do
  ATTEMPT=$((ATTEMPT + 1))
  echo "# probe $ATTEMPT t=$((SECONDS - START))s" >&2
  if timeout 75 python - <<'EOF' >/dev/null 2>&1
import sys
import jax
sys.exit(0 if any(d.platform.lower() == "tpu" for d in jax.devices()) else 1)
EOF
  then
    echo "# tunnel healthy; starting round-4 capture set" >&2

    # 1. headline bench (promotion convention lives in capture_lib.sh)
    capture_bench 900

    capture expand_probe 1800 python -m gpu_rscode_tpu.tools.expand_probe
    capture k_sweep 2400 python -m gpu_rscode_tpu.tools.k_sweep
    capture w16 900 python -m gpu_rscode_tpu.tools.w16_bench
    mkdir -p /dev/shm/rs_stream
    capture stream_tmpfs 1800 python -m gpu_rscode_tpu.tools.stream_bench \
      --mb 1024 --dir /dev/shm/rs_stream --seg-mb 128
    rm -rf /dev/shm/rs_stream
    capture inverse 900 python -m gpu_rscode_tpu.tools.inverse_bench
    echo "# round-4 capture set complete" >&2
    exit 0
  fi
  sleep 45
done
echo "# deadline reached without healthy tunnel" >&2
exit 2
