#!/bin/bash
# Round-4f: re-measure the kernel floors under the round-4 production
# formulation.  The committed compute-only ceiling (64.9 GB/s,
# kernel_floors_tpu_20260730T*) was measured on the OLD shift+sum body;
# the shipping kernel is now shift_raw + dot refold at 102.5 GB/s — past
# the old ceiling — so "X % of ceiling" claims need a fresh floor.
# Waits for r4d/r4e (one tunnel client at a time).
# Usage: tools/tpu_probe_r4f.sh [max_seconds]
set -u
LIB="$(cd "$(dirname "$0")" && pwd)/capture_lib.sh"
cd /root/repo
mkdir -p bench_captures
MAX=${1:-36000}
START=$SECONDS
ATTEMPT=0
. "$LIB"

while pgrep -f "tpu_probe_r4[de].sh" >/dev/null 2>&1; do
  echo "# waiting for r4d/r4e to finish t=$((SECONDS - START))s" >&2
  sleep 60
  [ $((SECONDS - START)) -ge "$MAX" ] && { echo "# deadline" >&2; exit 2; }
done

while [ $((SECONDS - START)) -lt "$MAX" ]; do
  ATTEMPT=$((ATTEMPT + 1))
  echo "# probe $ATTEMPT t=$((SECONDS - START))s" >&2
  if timeout 75 python - <<'EOF' >/dev/null 2>&1
import sys
import jax
sys.exit(0 if any(d.platform.lower() == "tpu" for d in jax.devices()) else 1)
EOF
  then
    echo "# tunnel healthy; starting round-4f capture set" >&2
    capture kernel_floors_postflip 1200 \
      python -m gpu_rscode_tpu.tools.kernel_sweep \
      --mb 320 --trials 3 --bodies base,raw_dot --tiles 16384,32768
    echo "# round-4f capture set complete" >&2
    exit 0
  fi
  sleep 60
done
echo "# deadline reached without healthy tunnel" >&2
exit 2
