# Shared capture helper for the TPU probe/watch scripts — source from a
# script that has set START=$SECONDS and cd'd to the repo root.
#
#   capture <name> <timeout_s> <cmd...>
#
# Runs <cmd> under timeout, writes stdout to
# bench_captures/<name>_tpu_<utc>.jsonl and stderr to the matching .log,
# '#'-prefixes any non-JSON stdout lines (commented-jsonl convention),
# and git-commits the pair immediately — the tunnel can wedge at any
# moment, so every capture must be durable the instant it exists.
# Empty captures are removed, not committed.
capture() {
  local name=$1 tmo=$2; shift 2
  local ts
  ts=$(date -u +%Y%m%dT%H%M%SZ)
  local out="bench_captures/${name}_tpu_${ts}.jsonl"
  echo "# [$((SECONDS - START))s] capturing ${name} (timeout ${tmo}s)" >&2
  timeout "$tmo" "$@" > "$out" 2> "${out%.jsonl}.log"
  local rc=$?
  echo "# ${name} rc=${rc}" >&2
  sed -i -e '/^[{#]/!s/^/# /' "$out" 2>/dev/null
  if [ -s "$out" ]; then
    git add "$out" "${out%.jsonl}.log" 2>/dev/null
    git commit -q -m "TPU capture: ${name} (rc=${rc})" 2>/dev/null
  else
    rm -f "$out"
  fi
  return $rc
}
