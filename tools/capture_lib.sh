# Shared capture helper for the TPU probe/watch scripts — source from a
# script that has set START=$SECONDS and cd'd to the repo root.
#
#   capture <name> <timeout_s> <cmd...>
#
# Runs <cmd> under timeout, writes stdout to
# bench_captures/<name>_tpu_<utc>.jsonl and stderr to the matching .log,
# '#'-prefixes any non-JSON stdout lines (commented-jsonl convention),
# and git-commits the pair immediately — the tunnel can wedge at any
# moment, so every capture must be durable the instant it exists.
# Empty captures are removed, not committed.
capture() {
  local name=$1 tmo=$2; shift 2
  local ts
  ts=$(date -u +%Y%m%dT%H%M%SZ)
  local out="bench_captures/${name}_tpu_${ts}.jsonl"
  echo "# [$((SECONDS - START))s] capturing ${name} (timeout ${tmo}s)" >&2
  timeout "$tmo" "$@" > "$out" 2> "${out%.jsonl}.log"
  local rc=$?
  echo "# ${name} rc=${rc}" >&2
  sed -i -e '/^[{#]/!s/^/# /' "$out" 2>/dev/null
  if [ -s "$out" ]; then
    git add "$out" "${out%.jsonl}.log" 2>/dev/null
    git commit -q -m "TPU capture: ${name} (rc=${rc})" 2>/dev/null
  else
    rm -f "$out"
  fi
  return $rc
}

# capture_bench <timeout_s>
#
# The headline-bench convention, one copy: run bench.py, promote the pair
# to the bench_tpu_ prefix (what bench.py's committed-capture pointer
# globs for) ONLY when the artifact really carries a TPU metric, and
# git-commit either way so even a fallback attempt is auditable.
capture_bench() {
  local tmo=$1
  local ts
  ts=$(date -u +%Y%m%dT%H%M%SZ)
  echo "# [$((SECONDS - START))s] capturing headline bench (timeout ${tmo}s)" >&2
  timeout "$tmo" python bench.py \
    > "bench_captures/bench_${ts}.json" 2> "bench_captures/bench_${ts}.log"
  local rc=$?
  echo "# bench rc=${rc}" >&2
  # Promotion keys on the TOP-LEVEL metric name, anchored at line start
  # (bench.py's _emit always writes "metric" first): a CPU-fallback line
  # embeds a "latest_committed_tpu" evidence dict whose own inner
  # "metric" ends in _tpu, so any unanchored grep would mislabel a CPU
  # artifact as hardware.
  if [ -s "bench_captures/bench_${ts}.json" ] \
      && grep -Eq '^\{"metric": "[a-z0-9_]*_tpu"' \
           "bench_captures/bench_${ts}.json"; then
    mv "bench_captures/bench_${ts}.json" \
       "bench_captures/bench_tpu_${ts}.json"
    mv "bench_captures/bench_${ts}.log" \
       "bench_captures/bench_tpu_${ts}.log"
    git add "bench_captures/bench_tpu_${ts}.json" \
            "bench_captures/bench_tpu_${ts}.log"
    git commit -q -m "TPU capture: headline bench"
  else
    # Empty captures are removed, not committed (same rule as capture());
    # the .log alone still carries the audit value of a failed attempt.
    # The rm is gated ONLY on emptiness — a failed git add (e.g. a
    # concurrent watcher holding index.lock) must not destroy a
    # non-empty artifact.
    if [ -s "bench_captures/bench_${ts}.json" ]; then
      git add "bench_captures/bench_${ts}.json" 2>/dev/null
    else
      rm -f "bench_captures/bench_${ts}.json"
    fi
    git add "bench_captures/bench_${ts}.log" 2>/dev/null
    git commit -q -m "bench capture attempt (rc=${rc}, no TPU line)" \
      2>/dev/null
  fi
  return $rc
}
