#!/bin/bash
# Round-4d: the post-default-flip evidence set.  Watches the axon tunnel
# (wedged again after the r4c w16_raw capture, 2026-07-31 ~03:45); on the
# first healthy probe it captures, committing after every capture:
#   1. bench.py headline — the driver-identical artifact under the new
#      shift_raw + dot production defaults (expected ~100 GB/s vs the
#      61.9 recorded pre-flip).
#   2. w16 with explicit refold=sum — baseline for (3).
#   3. w16 with explicit refold=dot LAST — the r4c w16_raw_dot capture
#      died at the 900 s timeout with the tunnel wedging right after, so
#      hang-vs-tunnel is unresolved; if this combo genuinely hangs the
#      w16 default must not be dot (and pallas_gemm.py keeps "sum" there
#      until this capture lands).
# Usage: tools/tpu_probe_r4d.sh [max_seconds]
set -u
LIB="$(cd "$(dirname "$0")" && pwd)/capture_lib.sh"
cd /root/repo
mkdir -p bench_captures
MAX=${1:-36000}
START=$SECONDS
ATTEMPT=0
. "$LIB"

while [ $((SECONDS - START)) -lt "$MAX" ]; do
  ATTEMPT=$((ATTEMPT + 1))
  echo "# probe $ATTEMPT t=$((SECONDS - START))s" >&2
  if timeout 75 python - <<'EOF' >/dev/null 2>&1
import sys
import jax
sys.exit(0 if any(d.platform.lower() == "tpu" for d in jax.devices()) else 1)
EOF
  then
    echo "# tunnel healthy; starting round-4d capture set" >&2

    ts=$(date -u +%Y%m%dT%H%M%SZ)
    timeout 900 python bench.py \
      > "bench_captures/bench_${ts}.json" 2> "bench_captures/bench_${ts}.log"
    brc=$?
    echo "# bench rc=${brc}" >&2
    if [ -s "bench_captures/bench_${ts}.json" ] \
        && grep -q '_tpu"' "bench_captures/bench_${ts}.json"; then
      # Keep the <stem>.json/<stem>.log pairing when promoting to the
      # bench_tpu_ prefix bench.py globs for.
      mv "bench_captures/bench_${ts}.json" \
         "bench_captures/bench_tpu_${ts}.json"
      mv "bench_captures/bench_${ts}.log" \
         "bench_captures/bench_tpu_${ts}.log"
      git add "bench_captures/bench_tpu_${ts}.json" \
              "bench_captures/bench_tpu_${ts}.log"
      git commit -q -m "TPU capture: headline bench, post-flip defaults"
    else
      git add "bench_captures/bench_${ts}.json" \
              "bench_captures/bench_${ts}.log" 2>/dev/null
      git commit -q -m "bench capture attempt (rc=${brc}, no TPU line)" \
        2>/dev/null
    fi

    W16=(python -m gpu_rscode_tpu.tools.w16_bench --trials 3)
    capture w16_raw_sum 900 \
      env RS_PALLAS_EXPAND=shift_raw RS_PALLAS_REFOLD=sum "${W16[@]}"
    capture w16_raw_dot2 900 \
      env RS_PALLAS_EXPAND=shift_raw RS_PALLAS_REFOLD=dot "${W16[@]}"
    echo "# round-4d capture set complete" >&2
    exit 0
  fi
  sleep 60
done
echo "# deadline reached without healthy tunnel" >&2
exit 2
