#!/bin/bash
# Round-4e: post-flip tuning + the nibble32 candidate.  Waits for any
# running r4d set to finish (one tunnel client at a time), then on the
# first healthy probe:
#   1. nibble32 verdict at k=10 — the reference's nibble-table idea
#      (gf16.h:1-22) carried entirely in int32 lanes, the only lane width
#      this Mosaic toolchain lowers; every narrower nibble attempt failed
#      legalization (r3/r4 captures).
#   2. tile x acc micro-sweep at the headline shape under shift_raw+dot —
#      the TPU_TILE=16384/int8-below-depth-256 defaults were measured
#      under shift+sum and may have moved with the refold off the VPU.
#   3. k_sweep rerun under the new production defaults (the committed
#      depth rule DEEP_CONTRACTION=256 was a sum-refold measurement).
# Usage: tools/tpu_probe_r4e.sh [max_seconds]
set -u
LIB="$(cd "$(dirname "$0")" && pwd)/capture_lib.sh"
cd /root/repo
mkdir -p bench_captures
MAX=${1:-36000}
START=$SECONDS
ATTEMPT=0
. "$LIB"

while pgrep -f "tpu_probe_r4d.sh" >/dev/null 2>&1; do
  echo "# waiting for r4d to finish t=$((SECONDS - START))s" >&2
  sleep 60
  [ $((SECONDS - START)) -ge "$MAX" ] && { echo "# deadline" >&2; exit 2; }
done

while [ $((SECONDS - START)) -lt "$MAX" ]; do
  ATTEMPT=$((ATTEMPT + 1))
  echo "# probe $ATTEMPT t=$((SECONDS - START))s" >&2
  if timeout 75 python - <<'EOF' >/dev/null 2>&1
import sys
import jax
sys.exit(0 if any(d.platform.lower() == "tpu" for d in jax.devices()) else 1)
EOF
  then
    echo "# tunnel healthy; starting round-4e capture set" >&2
    P=(python -m gpu_rscode_tpu.tools.expand_probe --trials 3)
    capture nibble32_k10 900 "${P[@]}" --expand shift_raw nibble32
    capture nibble32_k10_dot 900 "${P[@]}" --expand shift_raw nibble32 \
      --refold dot
    for tile in 8192 16384 32768 65536; do
      for acc in int8 bf16; do
        capture "tile_dot_k10_t${tile}_${acc}" 600 "${P[@]}" \
          --expand shift_raw --refold dot --tile "$tile" --acc "$acc"
      done
    done
    capture k_sweep_postflip 1800 python -m gpu_rscode_tpu.tools.k_sweep
    echo "# round-4e capture set complete" >&2
    exit 0
  fi
  sleep 60
done
echo "# deadline reached without healthy tunnel" >&2
exit 2
