#!/bin/bash
# Watch the axon tunnel; on the first healthy probe, run the round-4b
# probe set (tools/tpu_probe_r4b.sh).  Records every probe attempt so the
# tunnel-health history stays auditable (bench_captures/tunnel_probes_*).
set -u
cd /root/repo
MAX=${1:-36000}
START=$SECONDS
ATTEMPT=0
while [ $((SECONDS - START)) -lt "$MAX" ]; do
  ATTEMPT=$((ATTEMPT + 1))
  echo "# probe $ATTEMPT t=$((SECONDS - START))s" >&2
  if timeout 75 python - <<'EOF' >/dev/null 2>&1
import sys
import jax
sys.exit(0 if any(d.platform.lower() == "tpu" for d in jax.devices()) else 1)
EOF
  then
    echo "# tunnel healthy; running round-4b probes" >&2
    tools/tpu_probe_r4b.sh
    exit $?
  fi
  sleep 60
done
echo "# deadline reached without healthy tunnel" >&2
exit 2
